"""Mesh/sharding layouts for the scheduling pipeline (SURVEY §5.7/§5.8).

The framework's parallelism axes map onto a `jax.sharding.Mesh`:

- **nodes** — the data-parallel axis. Node-table blobs shard row-wise;
  per-(pod, node) masks/scores compute locally per shard; argmax and
  normalization reductions become XLA collectives riding ICI.
- **pods** — the batch axis. Pod blobs and per-pod outputs shard across
  it; phase-1 (parallel Filter/Score) is embarrassingly parallel in both
  axes at once, which is what the 2-D layout exploits on pods x nodes
  meshes (the commit scan stays sequential in pods by design, so the pods
  axis benefits phase-1 and the auction).

`pipeline_shardings` returns the canonical in_shardings for
`models.pipeline.schedule_batch` on either layout; the driver dryrun and
tests/test_multichip.py consume it so they cannot diverge.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.features import ClusterBlobs


def node_mesh(devices, name: str = "nodes") -> Mesh:
    """1-D mesh: every device holds a slice of the node table."""
    import numpy as np

    return Mesh(np.asarray(devices), (name,))


def pods_nodes_mesh(devices, pods_axis: int) -> Mesh:
    """2-D mesh [pods, nodes]: phase-1 work tiles over both axes."""
    import numpy as np

    devs = np.asarray(devices)
    assert devs.size % pods_axis == 0, \
        f"{devs.size} devices do not split into pods axis {pods_axis}"
    return Mesh(devs.reshape(pods_axis, devs.size // pods_axis),
                ("pods", "nodes"))


def mirror_shardings(mesh: Mesh) -> dict:
    """Sharding per Mirror device buffer: the node table shards row-wise on
    the 'nodes' mesh axis (the framework's data-parallel axis); the pod
    table replicates (topology kernels gather it by slot from every shard).
    Passing this to ``Mirror(mesh=...)`` makes every production launch —
    the batched pipeline, the usage chain, the preemption sweeps — run
    SPMD over the mesh: placements are bit-identical to single-device
    (tests/test_multichip.py), reductions ride ICI."""
    sh_nodes = NamedSharding(mesh, P("nodes", None))
    sh_rep = NamedSharding(mesh, P())
    return {"node_f32": sh_nodes, "node_i32": sh_nodes, "pods_i32": sh_rep}


def pipeline_shardings(mesh: Mesh, pblobs, wk, weights):
    """in_shardings for schedule_batch(cblobs, pblobs, wk, weights) on a
    ('nodes',) or ('pods', 'nodes') mesh: node-table blobs shard on the
    node axis, pod blobs shard on the pods axis when present, small
    operands replicate."""
    has_pods = "pods" in mesh.axis_names
    sh_nodes = NamedSharding(mesh, P("nodes", None))
    sh_pods = NamedSharding(mesh, P("pods", None)) if has_pods else None
    sh_rep = NamedSharding(mesh, P())
    cluster_sh = ClusterBlobs(node_f32=sh_nodes, node_i32=sh_nodes,
                              pods_i32=sh_rep)
    pod_sh = jax.tree_util.tree_map(
        lambda _: sh_pods if has_pods else sh_rep, pblobs)
    wk_sh = {k: sh_rep for k in wk}
    w_sh = jax.tree_util.tree_map(lambda _: sh_rep, weights)
    return (cluster_sh, pod_sh, wk_sh, w_sh)
