from kubernetes_tpu.config.types import (  # noqa: F401
    Plugin,
    PluginSet,
    Plugins,
    SchedulerConfiguration,
    SchedulerProfile,
    default_config,
    default_plugins,
)
from kubernetes_tpu.config.validation import validate_config  # noqa: F401
