"""Configuration validation — the reference's apis/config/validation
(validation.go ValidateKubeSchedulerConfiguration) re-derived for this
config surface: scalar ranges, feature gates, profile uniqueness +
queue-sort uniformity (profile/profile.go:47-66 NewMap), per-profile
plugin existence/weights, scoring-strategy args, and extender entries."""

from __future__ import annotations

from kubernetes_tpu.config.types import (
    PLUGIN_SET_FIELDS as _POINTS,
    SchedulerConfiguration,
)

_FIT_STRATEGIES = ("LeastAllocated", "MostAllocated",
                   "RequestedToCapacityRatio")


def _validate_fit_args(prefix: str, args: dict, errs: list[str]) -> None:
    """NodeResourcesFitArgs (validation/validation_pluginargs.go); key
    spelling matches what Framework.fit_scoring actually reads
    (snake_case, runtime.py)."""
    ss = args.get("scoring_strategy")
    if ss is None:
        return
    stype = ss.get("type", "LeastAllocated")
    if stype not in _FIT_STRATEGIES:
        errs.append(f"{prefix}: scoring_strategy.type {stype!r} must be one "
                    f"of {', '.join(_FIT_STRATEGIES)}")
    shape = (ss.get("requested_to_capacity_ratio") or {}).get("shape", [])
    if stype == "RequestedToCapacityRatio" and not shape:
        errs.append(f"{prefix}: RequestedToCapacityRatio requires a "
                    "non-empty shape")
    last = None
    for pt in shape:
        u, s = pt.get("utilization", 0), pt.get("score", 0)
        if not 0 <= u <= 100:
            errs.append(f"{prefix}: shape utilization {u} not in [0, 100]")
        if not 0 <= s <= 10:
            errs.append(f"{prefix}: shape score {s} not in [0, 10]")
        if last is not None and u <= last:
            errs.append(f"{prefix}: shape utilization must be strictly "
                        "increasing")
        last = u


def _validate_extenders(cfg: SchedulerConfiguration,
                        errs: list[str]) -> None:
    """validation.go validateExtenders: url required; weight must be
    positive only when a prioritize verb makes it meaningful."""
    for i, e in enumerate(cfg.extenders):
        prefix = f"extenders[{i}]"
        if not getattr(e, "url_prefix", ""):
            errs.append(f"{prefix}: url_prefix is required")
        if (getattr(e, "prioritize_verb", "")
                and getattr(e, "weight", 1.0) <= 0):
            errs.append(f"{prefix}: weight must be positive")
        if getattr(e, "timeout_seconds", 1.0) <= 0:
            errs.append(f"{prefix}: timeout_seconds must be positive")


def validate_config(cfg: SchedulerConfiguration,
                    registry: dict | None = None) -> list[str]:
    """Returns a list of error strings (empty = valid)."""
    errs: list[str] = []
    if cfg.parallelism <= 0:
        errs.append("parallelism must be positive")
    if cfg.batch_size <= 0:
        errs.append("batch_size must be positive")
    if cfg.binding_workers <= 0:
        errs.append("binding_workers must be positive")
    if cfg.node_capacity <= 0 or cfg.pod_table_capacity <= 0:
        errs.append("mirror capacities must be positive")
    if cfg.flight_recorder_capacity < 0:
        errs.append("flight_recorder_capacity must be >= 0 (0 disables)")
    if getattr(cfg, "trace_export_max_bytes", 0) < 0:
        errs.append("trace_export_max_bytes must be >= 0 (0 = unbounded)")
    if not 0 <= getattr(cfg, "tie_break_seed", 0) < 2 ** 32:
        errs.append("tie_break_seed must fit in uint32")
    from kubernetes_tpu.config.types import KNOWN_FEATURE_GATES

    for gate in cfg.feature_gates:
        if gate not in KNOWN_FEATURE_GATES:
            errs.append(f"unknown feature gate {gate!r}")
    if cfg.pod_initial_backoff_seconds <= 0:
        errs.append("pod_initial_backoff_seconds must be positive")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errs.append("pod_max_backoff_seconds must be >= initial backoff")
    if (cfg.percentage_of_nodes_to_score is not None
            and not 0 <= cfg.percentage_of_nodes_to_score <= 100):
        errs.append("percentage_of_nodes_to_score must be in [0, 100]")
    if not cfg.profiles:
        errs.append("at least one profile is required")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        errs.append("duplicate profile schedulerName")
    for p in cfg.profiles:
        if not p.scheduler_name:
            errs.append("profile schedulerName must be non-empty")
    if registry is not None and len(cfg.profiles) > 1:
        # queue-sort uniformity: one shared queue across profiles requires
        # one sort order (profile.go:57 "different queue sort plugins");
        # resolved with the runtime's own MultiPoint expansion so disabled
        # sets and custom sorts are honored
        from kubernetes_tpu.framework.runtime import expand_point

        sorts = {tuple(name for name, _ in
                       expand_point(prof, registry, "queue_sort"))
                 for prof in cfg.profiles}
        if len(sorts) > 1:
            errs.append("all profiles must use the same queueSort plugin set")
    _validate_extenders(cfg, errs)
    if registry is not None:
        for prof in cfg.profiles:
            for pt in _POINTS:
                for pl in getattr(prof.plugins, pt).enabled:
                    if pl.name not in registry:
                        errs.append(
                            f"profile {prof.scheduler_name}: unknown plugin "
                            f"{pl.name}")
                    if pl.weight < 0:
                        errs.append(f"plugin {pl.name}: negative weight")
                    if pl.weight > 100 and pt in ("score", "multi_point"):
                        # MaxWeight guard (validation.go); weight is inert
                        # on every other point (types.py Plugin)
                        errs.append(f"plugin {pl.name}: weight > 100")
            fit_args = prof.plugin_config.get("NodeResourcesFit")
            if fit_args:
                _validate_fit_args(
                    f"profile {prof.scheduler_name}: NodeResourcesFit",
                    fit_args, errs)
    return errs
