"""Configuration validation (apis/config/validation in the reference)."""

from __future__ import annotations

from kubernetes_tpu.config.types import SchedulerConfiguration


def validate_config(cfg: SchedulerConfiguration,
                    registry: dict | None = None) -> list[str]:
    """Returns a list of error strings (empty = valid)."""
    errs: list[str] = []
    if cfg.parallelism <= 0:
        errs.append("parallelism must be positive")
    if cfg.batch_size <= 0:
        errs.append("batch_size must be positive")
    from kubernetes_tpu.config.types import KNOWN_FEATURE_GATES

    for gate in cfg.feature_gates:
        if gate not in KNOWN_FEATURE_GATES:
            errs.append(f"unknown feature gate {gate!r}")
    if cfg.pod_initial_backoff_seconds <= 0:
        errs.append("pod_initial_backoff_seconds must be positive")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errs.append("pod_max_backoff_seconds must be >= initial backoff")
    if (cfg.percentage_of_nodes_to_score is not None
            and not 0 <= cfg.percentage_of_nodes_to_score <= 100):
        errs.append("percentage_of_nodes_to_score must be in [0, 100]")
    if not cfg.profiles:
        errs.append("at least one profile is required")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        errs.append("duplicate profile schedulerName")
    if registry is not None:
        for prof in cfg.profiles:
            sets = [getattr(prof.plugins, pt) for pt in (
                "pre_enqueue", "queue_sort", "pre_filter", "filter",
                "post_filter", "pre_score", "score", "reserve", "permit",
                "pre_bind", "bind", "post_bind", "multi_point")]
            for ps in sets:
                for pl in ps.enabled:
                    if pl.name not in registry:
                        errs.append(
                            f"profile {prof.scheduler_name}: unknown plugin "
                            f"{pl.name}")
                    if pl.weight < 0:
                        errs.append(f"plugin {pl.name}: negative weight")
    return errs
