"""Scheduler component configuration.

From-scratch equivalent of KubeSchedulerConfiguration
(/root/reference/pkg/scheduler/apis/config/types.go:37-190) with the same
semantics for profiles, per-extension-point plugin enable/disable sets, the
MultiPoint shorthand, and score weights — plus the TPU-build's own knobs
(batch size, capacity bucket hints).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

DEFAULT_SCHEDULER_NAME = "default-scheduler"

EXTENSION_POINTS = (
    "pre_enqueue", "queue_sort", "pre_filter", "filter", "post_filter",
    "pre_score", "score", "reserve", "permit", "pre_bind", "bind",
    "post_bind",
)

# the PluginSet fields on Plugins: every extension point + the MultiPoint
# shorthand (config load and validation iterate this, types.go:133-190)
PLUGIN_SET_FIELDS = EXTENSION_POINTS + ("multi_point",)


@dataclass
class Plugin:
    """One enabled/disabled plugin entry (types.go Plugin): name + Score
    weight (only meaningful on the score / multi_point sets)."""

    name: str
    weight: float = 0.0


@dataclass
class PluginSet:
    """enabled extends defaults; disabled removes them ("*" wipes all)
    (types.go PluginSet)."""

    enabled: list[Plugin] = field(default_factory=list)
    disabled: list[Plugin] = field(default_factory=list)


def _ps() -> PluginSet:
    return PluginSet()


@dataclass
class Plugins:
    """Plugin sets per extension point + the MultiPoint shorthand
    (types.go:133-190)."""

    pre_enqueue: PluginSet = field(default_factory=_ps)
    queue_sort: PluginSet = field(default_factory=_ps)
    pre_filter: PluginSet = field(default_factory=_ps)
    filter: PluginSet = field(default_factory=_ps)
    post_filter: PluginSet = field(default_factory=_ps)
    pre_score: PluginSet = field(default_factory=_ps)
    score: PluginSet = field(default_factory=_ps)
    reserve: PluginSet = field(default_factory=_ps)
    permit: PluginSet = field(default_factory=_ps)
    pre_bind: PluginSet = field(default_factory=_ps)
    bind: PluginSet = field(default_factory=_ps)
    post_bind: PluginSet = field(default_factory=_ps)
    multi_point: PluginSet = field(default_factory=_ps)


@dataclass
class SchedulerProfile:
    """One named scheduler within the process (types.go:100)."""

    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    plugins: Plugins = field(default_factory=Plugins)
    # plugin name -> args object (types_pluginargs.go); plain dicts here
    plugin_config: dict[str, dict[str, Any]] = field(default_factory=dict)


@dataclass
class SchedulerConfiguration:
    """Top-level component config (types.go:37-97)."""

    parallelism: int = 16
    profiles: list[SchedulerProfile] = field(default_factory=list)
    # percentageOfNodesToScore (schedule_one.go:668): None (default) scores
    # every node — on TPU one fused launch covers the full node set for the
    # same cost, so truncation buys nothing and loses placement quality.
    # When SET, the serial scan reproduces the reference's rotating
    # feasible-window selection (0 = the adaptive 50-nodes/125 formula)
    percentage_of_nodes_to_score: Optional[int] = None
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    # legacy HTTP extenders (extender.ExtenderConfig entries)
    extenders: list = field(default_factory=list)
    # feature gates (the component-base featuregate surface the perf
    # configs toggle): unknown gates rejected by validation
    feature_gates: dict[str, bool] = field(default_factory=dict)
    # binding cycle: runs on a worker pool after assume+permit
    # (schedule_one.go:124's per-pod goroutine)
    async_binding: bool = True
    binding_workers: int = 4
    # TPU-build knobs
    batch_size: int = 256       # pods scored per XLA launch
    node_capacity: int = 1024   # initial mirror bucket (grows by pow2)
    pod_table_capacity: int = 4096
    # multi-tenant job queues (backend/jobqueue.py): tenant name ->
    # {"weight": float, "quota": {resource: quantity}}. Pods carrying
    # the queue/pod-group labels route through the job-queue layer;
    # unknown tenants are created on demand with weight 1 and no quota
    tenants: dict[str, dict] = field(default_factory=dict)
    # flight recorder (always-on per-phase cycle tracing): ring size in
    # cycles; 0 disables the recorder entirely (not recommended — the
    # overhead budget is <2% of cycle time, see bench.py --trace-overhead)
    flight_recorder_capacity: int = 256
    # per-pod lifecycle timelines LRU (utils/tracing.PodTimelines):
    # time-to-bind SLO stats (telemetry/slo.py) walk this, so runs that
    # gate on p50/p99 across >4096 pods must size it to the workload or
    # the oldest pods silently fall out of the percentile pass
    timelines_capacity: int = 4096
    # append each cycle trace as a JSON line here (offline analysis /
    # the learned-scorer replay dataset; export format v2 carries
    # per-pod placement rows)
    trace_export_path: Optional[str] = None
    # size-based keep-last-1 rotation bound for the trace export file
    # (0 = unbounded); long trace-collection runs must not fill the disk
    trace_export_max_bytes: int = 64 * 1024 * 1024
    # ALSO export each placement's chosen-node learned-feature vector
    # (the replay-training substrate). Opt-in: it compiles the feature
    # kernels into every launch and adds per-cycle D2H pulls + export
    # bytes — phase-timing-only export users should not pay for it
    trace_export_features: bool = False
    # ALSO export each placement's top-K alternative node scores
    # (export v3 "alt" rows — the counterfactual substrate behind
    # per-placement regret and the learn-loop's contextual-bandit
    # fine-tune). Opt-in like trace_export_features: it compiles a
    # [B, K] top_k into every launch and rides the existing per-cycle
    # device_get (no extra sync)
    trace_export_alts: bool = False
    # device-side gang packing (ops/gang.pack_gangs): place a whole
    # PodGroup in one fused launch — all-or-nothing feasibility on
    # device, one host commit, no per-member Permit round-trips. Off
    # routes every gang through the host Permit-quorum path (the
    # differential-test arm; the fallback ladder lands here too)
    gang_device_packing: bool = True
    # pipelined scheduling waves: keep PIPELINE_DEPTH launches in flight
    # (wave N's commit pull rides a commit thread and overlaps wave N+1's
    # device time), patch informer churn into the device-resident
    # free/nzr chain in place of whole-chain invalidation, and re-dispatch
    # preemptors into the next wave the moment their eviction flush fires
    # (nominated reservations protect the slots). Off restores strict
    # launch->commit alternation with whole-chain invalidation on every
    # informer event — the differential A/B arm; placements are identical
    # under a fixed tie seed on churn-free workloads (the chain is the
    # same state either way, only its lifetime differs)
    pipelined_waves: bool = True
    # scheduler brownout (overload protection): when the hub answers a
    # sustained run of 429s (flow-control rejections) or queue-wait SLO
    # breaches, the scheduler sheds its own load instead of hammering a
    # saturated fabric — effective batch shrinks to
    # max(batch_size // brownout_batch_divisor, brownout_batch_floor),
    # the drift sentinel stretches its cadence by
    # brownout_drift_stretch, and best-effort tenants (weight <
    # brownout_besteffort_weight) are parked in the jobqueue. Exits
    # after brownout_clear_windows consecutive maintenance windows with
    # no new throttles. brownout_throttle_threshold <= 0 disables.
    brownout_throttle_threshold: int = 8
    brownout_clear_windows: int = 3
    brownout_batch_divisor: int = 4
    brownout_batch_floor: int = 8
    brownout_drift_stretch: float = 4.0
    brownout_besteffort_weight: float = 0.25
    # SLO watchdog (telemetry/watchdog.py): evaluated on the maintenance
    # cadence, at most every watchdog_interval_s. watchdog_slo is a
    # telemetry/slo.py target dict over live time-to-bind stats (e.g.
    # {"time_to_bind_p99_ms": 500}); empty = no SLO rule (containment
    # incidents still fire). watchdog_min_binds gates the SLO rule until
    # enough pods bound for percentiles to mean anything
    watchdog_interval_s: float = 5.0
    watchdog_slo: dict[str, float] = field(default_factory=dict)
    watchdog_min_binds: int = 8
    # incident autopsy (telemetry/autopsy.py): directory for black-box
    # bundles captured when a watchdog rule trips or a containment site
    # fires. None disables capture (the watchdog still counts incidents
    # in scheduler_watchdog_incidents_total). Retention: newest
    # autopsy_max_bundles bundles / autopsy_max_bytes on disk; at most
    # one bundle per incident class per autopsy_rate_limit_s
    autopsy_dir: Optional[str] = None
    autopsy_max_bundles: int = 32
    autopsy_max_bytes: int = 16 * 1024 * 1024
    autopsy_rate_limit_s: float = 30.0
    # explicit tie-break RNG seed for the device pipeline's equal-score
    # node choice: paired A/B runs (bench --ab-scorer) share a seed so
    # placement diffs are attributable to the scorer, not the coin.
    # 0 = the historical default hash stream.
    tie_break_seed: int = 0

    def gate(self, name: str, default: bool = True) -> bool:
        return self.feature_gates.get(name, default)

    def profile(self, scheduler_name: str) -> Optional[SchedulerProfile]:
        for p in self.profiles:
            if p.scheduler_name == scheduler_name:
                return p
        return None


# default enablement + weights: apis/config/v1/default_plugins.go:30-58,
# expressed through MultiPoint exactly like the reference
DEFAULT_MULTI_POINT = (
    ("SchedulingGates", 0),
    ("PrioritySort", 0),
    ("NodeUnschedulable", 0),
    ("NodeName", 0),
    ("TaintToleration", 3),
    ("NodeAffinity", 2),
    ("NodePorts", 0),
    ("NodeResourcesFit", 1),
    ("VolumeRestrictions", 0),
    ("NodeVolumeLimits", 0),
    ("VolumeBinding", 0),
    ("VolumeZone", 0),
    ("DynamicResources", 0),
    ("PodTopologySpread", 2),
    ("InterPodAffinity", 2),
    ("DefaultPreemption", 0),
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("GangScheduling", 0),
    ("DefaultBinder", 0),
)


# gates this build understands (both default ON, like current upstream)
KNOWN_FEATURE_GATES = ("SchedulerQueueingHints", "SchedulerAsyncPreemption")


def default_plugins() -> Plugins:
    return Plugins(multi_point=PluginSet(
        enabled=[Plugin(name, weight) for name, weight in DEFAULT_MULTI_POINT]))


def default_config() -> SchedulerConfiguration:
    return SchedulerConfiguration(profiles=[
        SchedulerProfile(plugins=default_plugins())])
