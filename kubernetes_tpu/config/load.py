"""Component config file loading.

The slice of cmd/kube-scheduler's options/config plumbing
(app/server.go:89 Setup + apis/config loading) this build needs: a JSON
(or YAML, when available) KubeSchedulerConfiguration-shaped document maps
onto SchedulerConfiguration — profiles with per-point plugin sets,
plugin args, extenders, and the TPU-build knobs.
"""

from __future__ import annotations

import json

from kubernetes_tpu.config.types import (
    Plugin,
    Plugins,
    PluginSet,
    SchedulerConfiguration,
    SchedulerProfile,
    default_plugins,
)
from kubernetes_tpu.config.types import PLUGIN_SET_FIELDS as _POINTS
from kubernetes_tpu.extender import ExtenderConfig


def _plugin_set(doc: dict) -> PluginSet:
    def entries(items):
        return [Plugin(name=e["name"], weight=e.get("weight", 0.0))
                for e in items or []]

    return PluginSet(enabled=entries(doc.get("enabled")),
                     disabled=entries(doc.get("disabled")))


def _profile(doc: dict) -> SchedulerProfile:
    plugins = default_plugins()
    pdoc = doc.get("plugins") or {}
    if pdoc.get("multi_point", {}).get("replace_defaults"):
        plugins = Plugins()
    for point in _POINTS:
        if point in pdoc:
            ps = _plugin_set(pdoc[point])
            cur = getattr(plugins, point)
            cur.enabled.extend(ps.enabled)
            cur.disabled.extend(ps.disabled)
    cfg = {}
    for entry in doc.get("plugin_config") or []:
        cfg[entry["name"]] = entry.get("args") or {}
    return SchedulerProfile(
        scheduler_name=doc.get("scheduler_name", "default-scheduler"),
        plugins=plugins, plugin_config=cfg)


def config_from_dict(doc: dict) -> SchedulerConfiguration:
    cfg = SchedulerConfiguration()
    for key in ("parallelism", "percentage_of_nodes_to_score",
                "pod_initial_backoff_seconds", "pod_max_backoff_seconds",
                "async_binding", "binding_workers", "batch_size",
                "node_capacity", "pod_table_capacity",
                "flight_recorder_capacity", "trace_export_path",
                "trace_export_max_bytes", "trace_export_features",
                "trace_export_alts", "tie_break_seed"):
        if key in doc:
            setattr(cfg, key, doc[key])
    profiles = [_profile(p) for p in doc.get("profiles") or []]
    if not profiles:
        profiles = [SchedulerProfile(plugins=default_plugins())]
    cfg.profiles = profiles
    cfg.feature_gates = dict(doc.get("feature_gates") or {})
    cfg.extenders = [ExtenderConfig(
        url_prefix=e["url_prefix"],
        filter_verb=e.get("filter_verb", ""),
        prioritize_verb=e.get("prioritize_verb", ""),
        bind_verb=e.get("bind_verb", ""),
        preempt_verb=e.get("preempt_verb", ""),
        weight=e.get("weight", 1.0),
        managed_resources=e.get("managed_resources") or [],
        ignorable=e.get("ignorable", False),
        node_cache_capable=e.get("node_cache_capable", False),
        timeout_seconds=e.get("timeout_seconds", 5.0))
        for e in doc.get("extenders") or []]
    return cfg


def load_config(path: str) -> SchedulerConfiguration:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml

            doc = yaml.safe_load(text)
        except ImportError as e:
            raise ValueError(
                f"{path}: not valid JSON and no YAML support") from e
    return config_from_dict(doc or {})
