"""Scheduler metrics: counters, gauges, histograms + the async recorder.

From-scratch equivalent of /root/reference/pkg/scheduler/metrics/
metrics.go:147-335 (the metric set) and metric_recorder.go (the buffered
MetricAsyncRecorder that keeps observation off the hot path). Metric names
and label sets mirror the reference so dashboards/thresholds port over;
the registry snapshots to a dict and renders Prometheus text for the
serving endpoint (kubernetes_tpu.serving).
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from typing import Callable, Optional

# k8s histogram buckets: exponential 0.001s..~16s (metrics.go power-of-2)
DURATION_BUCKETS = tuple(0.001 * (2 ** i) for i in range(15))
# flight-recorder phases and per-plugin timings live in the 10us..10s
# range (a host dict probe is microseconds, a DRA allocation
# milliseconds) — finer low end than the reference's 1ms floor
FINE_DURATION_BUCKETS = tuple(0.00001 * (2 ** i) for i in range(21))
ATTEMPTS_BUCKETS = (1, 2, 4, 8, 16)
VICTIMS_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def _labels_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    def __init__(self, name: str, help_: str = "",
                 label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = _labels_key(labels)
        self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def snapshot(self):
        return {str(dict(k)): v for k, v in self._values.items()}


class Gauge:
    """A gauge whose value may be pulled from a callback at snapshot time
    (pending_pods reads the queue's live counts)."""

    def __init__(self, name: str, help_: str = "",
                 fn: Optional[Callable[[], dict[str, float]]] = None):
        self.name = name
        self.help = help_
        self._fn = fn
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        self._values[_labels_key(labels)] = value

    def collect(self) -> dict[tuple, float]:
        if self._fn is not None:
            return {_labels_key({"queue": k}): float(v)
                    for k, v in self._fn().items()}
        return dict(self._values)

    def snapshot(self):
        return {str(dict(k)): v for k, v in self.collect().items()}


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple[float, ...] = DURATION_BUCKETS,
                 label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self.label_names = label_names
        # per-label-set: (bucket counts [len+1], sum, count)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, n: int = 1, **labels) -> None:
        """Record ``value`` ``n`` times (n>1 = the batched loop attributing
        one per-pod value to a whole batch without n histogram walks)."""
        k = _labels_key(labels)
        s = self._series.get(k)
        if s is None:
            s = self._series[k] = [[0] * (len(self.buckets) + 1), 0.0, 0]
        idx = bisect.bisect_left(self.buckets, value)
        s[0][idx] += n
        s[1] += value * n
        s[2] += n

    def count(self, **labels) -> int:
        s = self._series.get(_labels_key(labels))
        return s[2] if s else 0

    def total_count(self) -> int:
        return sum(s[2] for s in self._series.values())

    def percentile(self, q: float, **labels) -> float:
        """Bucket-resolution percentile (what perf-dash reads from the
        histogram_quantile of these series)."""
        if labels:
            series = [self._series.get(_labels_key(labels))]
            series = [s for s in series if s]
        else:
            series = list(self._series.values())
        if not series:
            return 0.0
        counts = [0] * (len(self.buckets) + 1)
        total = 0
        for s in series:
            total += s[2]
            for i, c in enumerate(s[0]):
                counts[i] += c
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= rank:
                return self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1] * 2
        return self.buckets[-1] * 2

    def snapshot(self):
        return {str(dict(k)): {"count": s[2], "sum": round(s[1], 6)}
                for k, s in self._series.items()}


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}

    def register(self, metric):
        self._metrics[metric.name] = metric
        return metric

    def get(self, name: str):
        return self._metrics.get(name)

    def snapshot(self) -> dict:
        return {name: m.snapshot() for name, m in self._metrics.items()}

    def render_text(self) -> str:
        """Prometheus exposition format (the /metrics endpoint body)."""
        out = []
        for name, m in self._metrics.items():
            if m.help:
                out.append(f"# HELP {name} {_escape_help(m.help)}")
            if isinstance(m, Counter):
                out.append(f"# TYPE {name} counter")
                for k, v in m._values.items():
                    out.append(f"{name}{_fmt_labels(dict(k))} {v}")
            elif isinstance(m, Gauge):
                out.append(f"# TYPE {name} gauge")
                for k, v in m.collect().items():
                    out.append(f"{name}{_fmt_labels(dict(k))} {v}")
            elif isinstance(m, Histogram):
                out.append(f"# TYPE {name} histogram")
                for k, s in m._series.items():
                    labels = dict(k)
                    acc = 0
                    for i, b in enumerate(m.buckets):
                        acc += s[0][i]
                        le = dict(labels, le=str(b))
                        out.append(f"{name}_bucket{_fmt_labels(le)} {acc}")
                    le = dict(labels, le="+Inf")
                    out.append(f"{name}_bucket{_fmt_labels(le)} {s[2]}")
                    out.append(f"{name}_sum{_fmt_labels(labels)} {s[1]}")
                    out.append(f"{name}_count{_fmt_labels(labels)} {s[2]}")
        return "\n".join(out) + "\n"


def _escape_label_value(v: str) -> str:
    """Prometheus exposition-format label escaping: backslash, double
    quote and line feed must be escaped inside label values (the spec's
    only three escapes) — a plugin name or failure message containing
    any of them would otherwise emit unparseable exposition text."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and line feed (not double quote)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class SchedulerMetrics:
    """The reference's metric set (metrics.go:147-335), registered on one
    registry and exposed as attributes."""

    def __init__(self, pending_fn: Optional[Callable] = None):
        r = self.registry = Registry()
        self.schedule_attempts = r.register(Counter(
            "schedule_attempts_total",
            "Number of attempts to schedule pods, by result",
            ("result", "profile")))
        self.attempt_duration = r.register(Histogram(
            "scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (per pod, amortized over its batch)",
            DURATION_BUCKETS, ("result",)))
        self.algorithm_duration = r.register(Histogram(
            "scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency (the device launch)"))
        self.batch_duration = r.register(Histogram(
            "scheduling_cycle_duration_seconds",
            "One batched scheduling cycle end to end"))
        self.extension_point_duration = r.register(Histogram(
            "framework_extension_point_duration_seconds",
            "Per extension point latency", DURATION_BUCKETS,
            ("extension_point",)))
        self.pod_scheduling_attempts = r.register(Histogram(
            "pod_scheduling_attempts",
            "Attempts needed to schedule a pod", ATTEMPTS_BUCKETS))
        # flight recorder: per-phase cycle attribution + per-plugin
        # timing + the reference's e2e pod scheduling latency
        # (metrics.go pod_scheduling_duration_seconds /
        # plugin_execution_duration_seconds, never reproduced until now)
        self.phase_duration = r.register(Histogram(
            "scheduling_phase_duration_seconds",
            "Per-phase scheduling cycle latency from the always-on "
            "flight recorder", FINE_DURATION_BUCKETS, ("phase",)))
        self.plugin_duration = r.register(Histogram(
            "plugin_execution_duration_seconds",
            "Per-plugin execution latency by extension point (host "
            "plugins; device plugins are fused into one launch)",
            FINE_DURATION_BUCKETS, ("plugin", "extension_point")))
        self.pod_e2e_duration = r.register(Histogram(
            "pod_scheduling_duration_seconds",
            "E2e latency from a pod's first scheduling attempt to its "
            "successful bind, by attempts needed",
            DURATION_BUCKETS, ("attempts",)))
        self.preemption_attempts = r.register(Counter(
            "preemption_attempts_total", "Preemption attempts"))
        self.preemption_victims = r.register(Histogram(
            "preemption_victims", "Number of victims per preemption",
            VICTIMS_BUCKETS))
        self.pending_pods = r.register(Gauge(
            "pending_pods", "Pending pods by queue", fn=pending_fn))
        # hub-client resilience + chaos surface (mirrored from
        # RemoteHub.resilience_stats / ChaosHub.chaos_stats each
        # maintenance tick; counters live in the transport layer, the
        # registry is the one exposition point)
        self.hub_degraded = r.register(Gauge(
            "scheduler_hub_degraded",
            "1 while the hub is unreachable (degraded mode)"))
        # gauges mirroring externally-owned counters, so no _total
        # suffix (Prometheus reserves it for true counters — rate()
        # over a mirrored gauge would misread restarts)
        self.hub_client_retries = r.register(Gauge(
            "hub_client_retries",
            "Transport-level retries issued by the hub client"))
        self.hub_client_watch_reconnects = r.register(Gauge(
            "hub_client_watch_reconnects",
            "Watch streams re-established after a cut"))
        self.hub_client_degraded_seconds = r.register(Gauge(
            "hub_client_degraded_seconds",
            "Cumulative seconds the hub client spent unreachable"))
        # watch-resume split (true counters: the scheduler mirrors the
        # client's monotonic counts by DELTA, so rate() stays honest)
        self.hub_watch_resumes = r.register(Counter(
            "hub_watch_resumes_total",
            "Watch reconnects resumed from since_rv (journal replay)"))
        self.hub_watch_relists = r.register(Counter(
            "hub_watch_relists_total",
            "Watch reconnects that fell back to a full relist"))
        # flow control + brownout (overload protection): 429s mirrored
        # by delta from the hub client; brownout is the scheduler's own
        # load-shed mode (enter/exit in scheduler._evaluate_brownout)
        self.hub_client_throttled = r.register(Counter(
            "hub_client_throttled_total",
            "Hub calls answered 429 by server-side flow control"))
        self.hub_client_throttle_retries = r.register(Counter(
            "hub_client_throttle_retries_total",
            "Throttled idempotent calls retried after the server's "
            "Retry-After hint"))
        self.brownout = r.register(Gauge(
            "scheduler_brownout",
            "1 while the scheduler sheds load (brownout mode)"))
        self.brownout_transitions = r.register(Counter(
            "scheduler_brownout_transitions_total",
            "Brownout mode transitions by phase (enter/exit)",
            ("phase",)))
        self.hub_journal_depth = r.register(Gauge(
            "hub_journal_depth",
            "Event journal ring depth by resource kind"))
        self.hub_journal_compacted_rv = r.register(Gauge(
            "hub_journal_compacted_rv",
            "Journal compaction watermark by resource kind"))
        self.dra_cel_errors = r.register(Counter(
            "dra_cel_errors_total",
            "CEL selector compile/eval errors by source object",
            ("source",)))
        # control-plane fabric (sharded hub + binary wire codec):
        # per-shard journal state mirrored from ShardedHub stats, and
        # per-codec wire traffic mirrored by delta from the hub
        # client's accounting (true counters — rate() stays honest)
        self.hub_shard_depth = r.register(Gauge(
            "hub_shard_depth",
            "Journal ring depth by hub shard (sharded hubs only)"))
        self.hub_shard_compacted_rv = r.register(Gauge(
            "hub_shard_compacted_rv",
            "Journal compaction watermark by hub shard"))
        self.hub_shard_commits = r.register(Counter(
            "hub_shard_commits_total",
            "Mutations committed by hub shard", ("shard",)))
        self.wire_codec_messages = r.register(Counter(
            "wire_codec_messages_total",
            "Hub-client wire messages by codec (bin1 = the fabric's "
            "binary codec, json = the fallback wire)", ("codec",)))
        self.wire_codec_bytes = r.register(Counter(
            "wire_codec_bytes_total",
            "Hub-client wire bytes by codec and direction",
            ("codec", "direction")))
        self.chaos_injected_faults = r.register(Gauge(
            "chaos_injected_faults",
            "Faults injected by an attached chaos layer, by kind"))
        # self-healing scheduling core: fencing, quarantine, the
        # device->host fallback ladder, the drift sentinel, and the
        # daemon keep-alive (true counters — all owned by this process)
        self.fenced_writes = r.register(Counter(
            "scheduler_fenced_writes_total",
            "Hub writes rejected because this scheduler's fencing epoch "
            "was deposed by a newer leader", ("verb",)))
        self.quarantined_pods = r.register(Gauge(
            "scheduler_quarantined_pods",
            "Pods currently parked in the poison-pod quarantine"))
        self.quarantines = r.register(Counter(
            "scheduler_quarantines_total",
            "Pods moved to quarantine after repeatedly faulting their "
            "batch", ("reason",)))
        self.device_fallbacks = r.register(Counter(
            "scheduler_device_fallbacks_total",
            "Batches degraded from the fused device launch to the host "
            "Filter/Score path after a device fault"))
        # horizontal scale-out: this replica's view of the slice ring
        self.sched_slices_owned = r.register(Gauge(
            "scheduler_slices_owned",
            "Namespace-ring slots this scheduler replica currently "
            "drains (0 = not participating or awaiting a slice)"))
        self.slice_rebalances = r.register(Counter(
            "scheduler_slice_rebalances_total",
            "Slice-map changes this replica converged its queues to "
            "(join/death of a peer, or its own join)"))
        self.foreign_pending_pods = r.register(Gauge(
            "scheduler_foreign_pending_pods",
            "Pending pods penned because their namespace hashes into "
            "a peer replica's slice"))
        # device-launch profiler (telemetry/profiler.py): XLA compile
        # attribution per bucket-shape transition + resident HBM bytes
        self.device_compiles = r.register(Counter(
            "scheduler_device_compiles_total",
            "XLA compiles of the fused launch, by attributed cause "
            "(first / rebucket / batch_bucket / topology_bucket / "
            "flags / unattributed)", ("cause",)))
        self.device_launch_shapes = r.register(Gauge(
            "scheduler_device_launch_shapes",
            "Distinct launch bucket shapes this process has dispatched"))
        self.device_live_buffer_bytes = r.register(Gauge(
            "scheduler_device_live_buffer_bytes",
            "Resident device-buffer bytes by buffer family (cluster "
            "tensors, pod batch, DRA inventories, learned params)"))
        # scenario replay driver (scenario/replay.py): trace events it
        # injected into this scheduler's hub, SLO-gate breaches, and
        # the last replay's trace-time bind tail
        self.scenario_events = r.register(Counter(
            "scheduler_scenario_events_total",
            "Trace events injected by the scenario replayer, by kind",
            ("kind",)))
        self.scenario_slo_breaches = r.register(Counter(
            "scheduler_scenario_slo_breaches_total",
            "Scenario SLO gate breaches, by gated metric", ("metric",)))
        self.scenario_time_to_bind_p99 = r.register(Gauge(
            "scheduler_scenario_time_to_bind_p99_seconds",
            "Trace-time p99 time-to-bind of the last scenario replay"))
        # SLO watchdog + incident autopsy (telemetry/watchdog.py,
        # telemetry/autopsy.py): incidents by class, bundle capture
        # accounting, and the on-disk store footprint
        self.watchdog_evals = r.register(Counter(
            "scheduler_watchdog_evals_total",
            "Watchdog rule-set evaluations run on the maintenance "
            "cadence"))
        self.watchdog_incidents = r.register(Counter(
            "scheduler_watchdog_incidents_total",
            "Incidents raised (watchdog rule trips + direct containment "
            "hooks), by incident class", ("kind",)))
        self.watchdog_rules_tripped = r.register(Counter(
            "scheduler_watchdog_rules_tripped_total",
            "Watchdog rule trips by rule name", ("rule",)))
        self.autopsy_bundles = r.register(Counter(
            "scheduler_autopsy_bundles_total",
            "Black-box autopsy bundles written to disk, by trigger "
            "incident class", ("trigger",)))
        self.autopsy_bundles_dropped = r.register(Counter(
            "scheduler_autopsy_bundles_dropped_total",
            "Autopsy captures skipped or bundles pruned, by reason "
            "(rate_limited / retention / write_error)", ("reason",)))
        self.autopsy_store_bytes = r.register(Gauge(
            "scheduler_autopsy_store_bytes",
            "Bytes currently held by the autopsy bundle store"))
        self.drift_detected = r.register(Counter(
            "scheduler_drift_detected_total",
            "Cache/mirror-vs-hub discrepancies found by the drift "
            "sentinel"))
        self.drift_repaired = r.register(Counter(
            "scheduler_drift_repaired_total",
            "Drift discrepancies repaired by targeted re-sync"))
        self.drift_rebuilds = r.register(Counter(
            "scheduler_drift_full_rebuilds_total",
            "Last-resort full mirror/snapshot rebuilds after targeted "
            "drift repair failed to converge"))
        self.cycle_crashes = r.register(Counter(
            "scheduler_cycle_crashes_total",
            "Scheduling-loop exceptions survived by the daemon "
            "keep-alive (each backs the loop off before retrying)"))
        self.condition_patches_dropped = r.register(Counter(
            "scheduler_condition_patches_dropped_total",
            "Pod condition patches dropped (degraded mode or fenced) "
            "instead of wedging the loop", ("reason",)))
        # gang scheduling + multi-tenant job queues
        self.gang_admitted = r.register(Counter(
            "scheduler_gang_admitted_total",
            "Gangs whose Permit quorum completed (all members released "
            "to the binding cycle together)"))
        self.gang_timeouts = r.register(Counter(
            "scheduler_gang_timeout_total",
            "Gang assemblies that hit their schedule timeout before "
            "min_member members reserved"))
        self.gang_rollbacks = r.register(Counter(
            "scheduler_gang_rollback_total",
            "Gang assemblies rolled back atomically (timeout, member "
            "failure, or poison quarantine) — every held reservation "
            "released, no partial gang placed"))
        self.gang_device_launches = r.register(Counter(
            "scheduler_gang_device_launches_total",
            "Fused gang-packing launches dispatched (each places a "
            "whole wave of PodGroups in ONE device program — O(1) "
            "launches per gang, not O(members))"))
        self.gang_fallbacks = r.register(Counter(
            "scheduler_gang_fallbacks_total",
            "Gang units routed to the host Permit-quorum path instead "
            "of the device packer, by reason", ("reason",)))
        self.tenant_queue_depth = r.register(Gauge(
            "scheduler_tenant_queue_depth",
            "Pods held in the job-queue layer by tenant"))
        self.tenant_quota_used = r.register(Gauge(
            "scheduler_tenant_quota_used",
            "Admission-time quota reservation by tenant and resource"))
        # learned scoring subsystem (plugins/learned.py + ops/learned.py)
        self.learned_checkpoint_version = r.register(Gauge(
            "scheduler_learned_checkpoint_version",
            "Active learned-scorer checkpoint version by profile "
            "(0 = none loaded)"))
        self.learned_reloads = r.register(Counter(
            "scheduler_learned_reloads_total",
            "Learned-scorer checkpoint hot-reloads (mtime change "
            "observed at snapshot-sync time); generation 0 = a manual "
            "publish, >0 = the learn-loop's gated promotion",
            ("profile", "generation")))
        self.learned_load_errors = r.register(Counter(
            "scheduler_learned_load_errors_total",
            "Learned-scorer checkpoint loads rejected (corrupt/"
            "mismatched file; the last good params keep serving)",
            ("profile",)))
        self.learned_magnitude = r.register(Histogram(
            "scheduler_learned_score_magnitude",
            "Mean |weighted learned-score term| per launch over "
            "feasible (pod, node) pairs — drift watch for the fused "
            "MLP term", (0.01, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0,
                         50.0, 100.0, 200.0, 500.0)))
        self.queue_incoming_pods = r.register(Counter(
            "queue_incoming_pods_total",
            "Pods added to scheduling queues by event/queue",
            ("event", "queue")))
        self.permit_wait_duration = r.register(Histogram(
            "permit_wait_duration_seconds",
            "Time spent waiting at permit", DURATION_BUCKETS, ("result",)))
        self.cache_size = r.register(Gauge(
            "cache_size", "Scheduler cache size by type"))


class AsyncRecorder:
    """metric_recorder.go MetricAsyncRecorder: observations buffer into a
    lock-free-ish deque and flush off the hot path (the daemon's
    maintenance tick, or an explicit flush)."""

    def __init__(self, flush_interval: float = 1.0,
                 now: Callable[[], float] = None):
        import time as _time

        self._buf: deque = deque()
        self._interval = flush_interval
        self._now = now or _time.time
        self._last_flush = 0.0
        self._lock = threading.Lock()

    def observe(self, metric: Histogram, value: float, **labels) -> None:
        self._buf.append((metric, value, labels))

    def inc(self, metric: Counter, amount: float = 1.0, **labels) -> None:
        self._buf.append((metric, ("inc", amount), labels))

    def flush(self, force: bool = True) -> int:
        now = self._now()
        if not force and now - self._last_flush < self._interval:
            return 0
        self._last_flush = now
        n = 0
        with self._lock:
            while self._buf:
                metric, value, labels = self._buf.popleft()
                if isinstance(value, tuple) and value[0] == "inc":
                    metric.inc(value[1], **labels)
                else:
                    metric.observe(value, **labels)
                n += 1
        return n
