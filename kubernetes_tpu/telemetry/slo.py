"""Time-to-bind SLO computation shared by bench quality rows and the
scenario replay driver.

One pass over ``PodTimelines.bind_latencies()`` yields the p50/p99/max
time-to-bind stats; ``evaluate_slo`` turns those stats plus a target
dict into a pass/fail verdict with per-metric breach details. The
scenario engine stores SLO targets in *trace time* — replaying a trace
at K× compression divides measured wall latencies by K before gating,
so the same filed trace produces the same verdict on a laptop and the
1-core CI box (``scale`` parameter).
"""

from __future__ import annotations

from typing import Iterable, Mapping


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank-interpolated percentile over an ascending list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def time_to_bind_stats(
    timelines,
    uids: Iterable[str] | None = None,
    scale: float = 1.0,
) -> dict:
    """p50/p99/max time-to-bind (ms) from a PodTimelines instance.

    ``uids`` restricts the pass to a subset (replay uses it to exclude
    warmup pods); ``scale`` converts wall latencies to trace time when
    replaying at a compression factor (trace_ms = wall_ms * scale).
    """
    lat = timelines.bind_latencies()
    if uids is not None:
        keep = set(uids)
        lat = {u: v for u, v in lat.items() if u in keep}
    vals = sorted(v * scale * 1e3 for v in lat.values())
    return {
        "count": len(vals),
        "time_to_bind_p50_ms": round(percentile(vals, 50), 2),
        "time_to_bind_p99_ms": round(percentile(vals, 99), 2),
        "time_to_bind_max_ms": round(vals[-1], 2) if vals else 0.0,
    }


def evaluate_slo(stats: Mapping, slo: Mapping | None) -> dict:
    """Gate ``stats`` against an SLO dict of metric -> max-allowed value.

    SLO keys are stat keys (e.g. ``time_to_bind_p99_ms``); unknown keys
    are reported as breaches so a typo'd gate fails loudly rather than
    silently passing. Returns {"ok": bool, "breaches": [...]} where each
    breach is {"metric", "value", "limit"}.
    """
    breaches = []
    for metric, limit in (slo or {}).items():
        value = stats.get(metric)
        if value is None or value > limit:
            breaches.append(
                {"metric": metric, "value": value, "limit": limit}
            )
    return {"ok": not breaches, "breaches": breaches}
