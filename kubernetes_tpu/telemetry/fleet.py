"""Fleet-wide metrics aggregation: strict exposition parsing + FleetView.

Every fabric component answers ``/metrics`` (Prometheus text exposition)
and ``/healthz``: the hub server and relay servers serve them off their
existing HTTP handlers, the kubemark feeder mounts
:class:`ComponentEndpoints`. :class:`FleetView` is the collector — it
pulls every endpoint, re-labels each sample with ``component``/``shard``
and merges everything into ONE exposition (the fleet scrape target) plus
a ``/debug/fleet`` topology-and-health summary.

The parser here is deliberately STRICT (``parse_exposition``): names and
labels must match the Prometheus grammar, label values must use the
spec's three escapes, values must be floats. It is both the merge's
ingest (a component emitting garbage is a loud per-endpoint error, not a
corrupted fleet exposition) and the metrics-lint test's oracle — the
scheduler's own ``/metrics`` body must round-trip through it, which
locks in the PR-4 escaping fix for every future metric.
"""

from __future__ import annotations

import re
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# one sample line: name{labels} value  (timestamp deliberately rejected
# — nothing in this stack emits one, so accepting it would just mask a
# component printing garbage that happens to look like a timestamp)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$")

# one label pair inside the braces; values are quoted with ONLY the
# spec's escapes (\\, \", \n) permitted
_LABEL_RE = re.compile(
    r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\\\|\\"|\\n)*)"')


@dataclass
class Sample:
    name: str
    labels: dict[str, str]
    value: float


@dataclass
class Exposition:
    """Parsed exposition: samples plus the HELP/TYPE metadata per
    metric family (family = the name without _bucket/_sum/_count)."""

    samples: list[Sample] = field(default_factory=list)
    help: dict[str, str] = field(default_factory=dict)
    type: dict[str, str] = field(default_factory=dict)


def _unescape_label(v: str) -> str:
    return v.replace("\\\\", "\x00").replace('\\"', '"') \
        .replace("\\n", "\n").replace("\x00", "\\")


def _parse_labels(raw: str) -> dict[str, str]:
    labels: dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ValueError(f"bad label pair at {raw[pos:pos + 40]!r}")
        labels[m.group("k")] = _unescape_label(m.group("v"))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                raise ValueError(f"expected ',' at {raw[pos:pos + 20]!r}")
            pos += 1
    return labels


def parse_exposition(text: str) -> Exposition:
    """Strictly parse a Prometheus text exposition; raises ValueError on
    ANY malformed line (the lint contract — silently skipping a bad line
    is how escaping bugs survive)."""
    out = Exposition()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_ = rest.partition(" ")
            if not METRIC_NAME_RE.match(name):
                raise ValueError(f"bad HELP metric name {name!r}")
            out.help[name] = help_
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, mtype = rest.partition(" ")
            if not METRIC_NAME_RE.match(name):
                raise ValueError(f"bad TYPE metric name {name!r}")
            if mtype not in ("counter", "gauge", "histogram", "summary",
                             "untyped"):
                raise ValueError(f"bad TYPE {mtype!r} for {name}")
            out.type[name] = mtype
            continue
        if line.startswith("#"):
            continue                      # plain comment
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable sample line {line!r}")
        name = m.group("name")
        labels = _parse_labels(m.group("labels")) \
            if m.group("labels") else {}
        for k in labels:
            if not LABEL_NAME_RE.match(k):
                raise ValueError(f"bad label name {k!r} on {name}")
        try:
            value = float(m.group("value"))
        except ValueError:
            raise ValueError(
                f"bad sample value {m.group('value')!r} on {name}") \
                from None
        out.samples.append(Sample(name, labels, value))
    return out


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_sample(s: Sample) -> str:
    if not s.labels:
        return f"{s.name} {s.value}"
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(s.labels.items()))
    return f"{s.name}{{{inner}}} {s.value}"


def merge_expositions(parts: list[tuple[dict, Exposition]]) -> str:
    """Merge parsed expositions into one body, each part's samples
    re-labeled with its injected labels (component/shard). TYPE/HELP
    come from the first part that declares them; injected labels keep
    same-named series from different components distinct."""
    help_: dict[str, str] = {}
    type_: dict[str, str] = {}
    by_family: dict[str, list[Sample]] = {}
    order: list[str] = []
    for inject, exp in parts:
        for name, h in exp.help.items():
            help_.setdefault(name, h)
        for name, t in exp.type.items():
            type_.setdefault(name, t)
        for s in exp.samples:
            fam = re.sub(r"_(bucket|sum|count)$", "", s.name)
            fam = fam if fam in exp.type else s.name
            if fam not in by_family:
                by_family[fam] = []
                order.append(fam)
            by_family[fam].append(
                Sample(s.name, {**s.labels, **inject}, s.value))
    lines: list[str] = []
    for fam in order:
        if fam in help_:
            lines.append(f"# HELP {fam} {help_[fam]}")
        if fam in type_:
            lines.append(f"# TYPE {fam} {type_[fam]}")
        lines.extend(_fmt_sample(s) for s in by_family[fam])
    return "\n".join(lines) + "\n"


# ---------------------- per-process identity ----------------------


IDENTITY_METRIC = "fabric_process_identity"


def process_identity_text(component: str,
                          port: Optional[int] = None) -> str:
    """The per-process identity sample every fabric component prefixes
    to its /metrics: pid + listen port as labels. Two shard processes
    of the same shard NAME (a restart landed on a new port, or an old
    incarnation lingers) stay distinguishable in the merged fleet
    exposition — the name alone used to collide."""
    import os

    labels = f'pid="{os.getpid()}"'
    if port is not None:
        labels += f',port="{port}"'
    return (f"# HELP {IDENTITY_METRIC} Process identity of this "
            f"fabric component\n"
            f"# TYPE {IDENTITY_METRIC} gauge\n"
            f"{IDENTITY_METRIC}{{{labels}}} 1\n")


def identity_of(exp: "Exposition") -> dict:
    """Extract {pid, port} from a parsed exposition's identity sample
    (empty when the component predates the identity stamp)."""
    for s in exp.samples:
        if s.name == IDENTITY_METRIC:
            out = {}
            if "pid" in s.labels:
                out["pid"] = int(s.labels["pid"])
            if "port" in s.labels:
                out["port"] = int(s.labels["port"])
            return out
    return {}


# ------------------------- component renderers -------------------------
#
# Each fabric component renders its own small Registry on demand; the
# metric sets are deliberately tiny (the scheduler's full set lives on
# its own /metrics — these are the FABRIC-side counters a fleet scrape
# needs to see per component).


def hub_metrics_text(hub) -> str:
    """The hub server's /metrics: revision space + per-kind journal
    depth, plus per-shard commits for a ShardedHub."""
    from kubernetes_tpu.metrics import Counter, Gauge, Registry

    r = Registry()
    rv = r.register(Gauge("hub_rv", "Newest committed revision"))
    depth = r.register(Gauge("hub_journal_depth",
                             "Event journal ring depth by resource kind"))
    compacted = r.register(Gauge(
        "hub_journal_compacted_rv",
        "Journal compaction watermark by resource kind"))
    commits = r.register(Counter("hub_shard_commits_total",
                                 "Mutations committed by hub shard",
                                 ("shard",)))
    st = hub.get_journal_stats()
    rv.set(float(st.get("rv", 0)))
    for kind, ks in st.get("kinds", {}).items():
        depth.set(float(ks["depth"]), kind=kind)
        compacted.set(float(ks["compacted_rv"]), kind=kind)
    for shard, ss in st.get("shards", {}).items():
        commits.inc(float(ss.get("commits", 0)), shard=shard)
    return r.render_text()


def relay_metrics_text(core) -> str:
    """A relay node's /metrics: fan-out counters + subscriber state."""
    from kubernetes_tpu.metrics import Counter, Gauge, Registry

    r = Registry()
    st = core.stats()
    subs = r.register(Gauge("relay_subscribers",
                            "Downstream subscribers attached"))
    last = r.register(Gauge("relay_last_rv",
                            "Newest upstream revision relayed"))
    g_in = r.register(Counter("relay_events_in_total",
                              "Events received from upstream"))
    g_out = r.register(Counter("relay_events_out_total",
                               "Events fanned out to subscribers"))
    ev = r.register(Counter("relay_slow_evictions_total",
                            "Slow subscribers evicted (bounded queues)"))
    res = r.register(Counter("relay_resume_serves_total",
                             "Downstream reconnects served off the ring"))
    rel = r.register(Counter("relay_relist_serves_total",
                             "Downstream LIST replays served from the "
                             "state mirror"))
    wd = r.register(Counter("relay_watchdog_reparents_total",
                            "Upstream deaths healed by watchdog "
                            "auto-reparent (cursor-carrying resume)"))
    subs.set(float(st["subscribers"]))
    last.set(float(st["last_rv"]))
    g_in.inc(float(st["events_in"]))
    g_out.inc(float(st["events_out"]))
    ev.inc(float(st["slow_evictions"]))
    res.inc(float(st["resume_serves"]))
    rel.inc(float(st["relist_serves"]))
    wd.inc(float(st.get("watchdog_reparents", 0)))
    return r.render_text()


def state_metrics_text(replica) -> str:
    """A state replica's /metrics rows: role (one series per replica,
    value 1 for the role it holds), term, and log/commit indexes — the
    fleet scrape's 'who leads, who lags' surface."""
    from kubernetes_tpu.metrics import Gauge, Registry

    r = Registry()
    role = r.register(Gauge("fabric_state_replica_role",
                            "State replica role (1 = holds the "
                            "labelled role)"))
    term = r.register(Gauge("fabric_state_term",
                            "State replication term at this replica"))
    log_idx = r.register(Gauge("fabric_state_log_index",
                               "Newest log index at this replica"))
    commit_idx = r.register(Gauge(
        "fabric_state_commit_index",
        "Newest majority-committed log index at this replica"))
    st = replica.fabric_replica_status()
    role.set(1.0, replica=st["name"], role=st["role"])
    term.set(float(st["term"]))
    log_idx.set(float(st["log_index"]))
    commit_idx.set(float(st["commit_index"]))
    return r.render_text()


def kubemark_metrics_text(hollow) -> str:
    """The kubemark feeder's /metrics: hollow-node count + acks."""
    from kubernetes_tpu.metrics import Counter, Gauge, Registry

    r = Registry()
    nodes = r.register(Gauge("kubemark_hollow_nodes",
                             "Hollow nodes registered by this feeder"))
    acked = r.register(Counter("kubemark_acked_pods_total",
                               "Pods this feeder drove to Running"))
    nodes.set(float(len(hollow.names)))
    acked.inc(float(hollow.ack_count()))
    return r.render_text()


class ComponentEndpoints:
    """A tiny /metrics + /healthz server for components without their
    own HTTP face (the kubemark feeder). ``metrics_fn`` renders the
    exposition body; ``healthz_fn`` (optional) returns True when
    healthy."""

    def __init__(self, metrics_fn: Callable[[], str],
                 healthz_fn: Optional[Callable[[], bool]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 component: str = "component"):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # quiet
                pass

            def _send(self, code: int, body: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 (stdlib API)
                path = self.path.partition("?")[0]
                if path == "/metrics":
                    self._send(200, process_identity_text(
                        outer.component,
                        self.server.server_address[1])
                        + outer.metrics_fn())
                elif path in ("/healthz", "/livez"):
                    ok = outer.healthz_fn() if outer.healthz_fn else True
                    self._send(200 if ok else 503,
                               "ok" if ok else "unhealthy")
                else:
                    self._send(404, "not found")

        self.metrics_fn = metrics_fn
        self.healthz_fn = healthz_fn
        self.component = component
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ComponentEndpoints":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="component-endpoints")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ------------------------------ FleetView ------------------------------


class FleetView:
    """The fleet collector: a static endpoint topology (component,
    shard, url), scraped on demand. ``render_text()`` is the merged
    exposition; ``summary()`` is the /debug/fleet payload (topology +
    per-endpoint health + scrape errors)."""

    def __init__(self, endpoints: list[dict], timeout: float = 5.0,
                 fetch: Optional[Callable[[str, float], str]] = None):
        for ep in endpoints:
            if "component" not in ep or "url" not in ep:
                raise ValueError(
                    f"fleet endpoint needs component+url: {ep!r}")
        self.endpoints = [dict(ep) for ep in endpoints]
        self.timeout = timeout
        self._fetch = fetch or self._http_fetch

    @classmethod
    def from_topology(cls, topology: dict, timeout: float = 5.0,
                      fetch: Optional[Callable[[str, float], str]] = None
                      ) -> "FleetView":
        """Build the endpoint list from a ``fabric_topology()`` payload
        instead of static config: routers, shards, relays, and (scale-
        out) the live scheduler-replica registry as role-``scheduler``
        rows. Components registered without a serving URL (headless
        test replicas) are skipped — a row that can never answer
        /healthz is noise, not topology."""
        endpoints: list[dict] = []
        for r in topology.get("routers", []):
            if r.get("url"):
                endpoints.append({"component": "router",
                                  "shard": r.get("name", ""),
                                  "url": r["url"]})
        for name, s in (topology.get("shards") or {}).items():
            if s.get("url"):
                endpoints.append({"component": "hub-shard",
                                  "shard": name, "url": s["url"]})
        for r in topology.get("relays", []):
            if r.get("url"):
                endpoints.append({"component": "relay",
                                  "shard": r.get("name", ""),
                                  "url": r["url"]})
        for name, s in (topology.get("schedulers") or {}).items():
            if s.get("url"):
                endpoints.append({"component": "scheduler",
                                  "shard": name, "url": s["url"],
                                  "role": "scheduler"})
        return cls(endpoints, timeout=timeout, fetch=fetch)

    @staticmethod
    def _http_fetch(url: str, timeout: float) -> str:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace")

    def scrape(self) -> list[dict]:
        """Pull every endpoint's /healthz and /metrics. Per-endpoint
        failures are REPORTED, never raised — one dead relay must not
        take down the fleet view of the living ones."""
        out: list[dict] = []
        for ep in self.endpoints:
            base = ep["url"].rstrip("/")
            rec = {"component": ep["component"],
                   "shard": ep.get("shard", ""),
                   "url": base, "healthy": False, "error": None,
                   "exposition": None, "scraped_at": time.time()}
            if ep.get("role"):
                # topology-declared role (scheduler replicas); state
                # replicas override from their self-reported sample
                rec["role"] = ep["role"]
            try:
                health = self._fetch(base + "/healthz", self.timeout)
                rec["healthy"] = health.strip().startswith("ok")
            except Exception as e:  # noqa: BLE001 — per-endpoint verdict
                rec["error"] = f"healthz: {e}"
                out.append(rec)
                continue
            try:
                body = self._fetch(base + "/metrics", self.timeout)
                rec["exposition"] = parse_exposition(body)
                rec["samples"] = len(rec["exposition"].samples)
                # per-process identity: pid + listen port distinguish
                # two incarnations sharing a component/shard name
                rec.update(identity_of(rec["exposition"]))
                # state replicas self-report their role — the summary's
                # 'who leads' column (a follower is healthy, not
                # degraded, and the row says which it is)
                for s in rec["exposition"].samples:
                    if s.name == "fabric_state_replica_role" \
                            and s.value == 1:
                        rec["role"] = s.labels.get("role")
            except Exception as e:  # noqa: BLE001 — strict parse verdict
                rec["error"] = f"metrics: {e}"
            out.append(rec)
        return out

    def render_text(self, records: Optional[list[dict]] = None) -> str:
        """The merged fleet exposition: every component's samples with
        ``component``/``shard`` labels injected. Pass ``records`` (a
        prior ``scrape()`` result) to derive both this and
        ``summary()`` from ONE round of HTTP round-trips."""
        parts = []
        for rec in (records if records is not None else self.scrape()):
            if rec["exposition"] is None:
                continue
            inject = {"component": rec["component"]}
            if rec["shard"]:
                inject["shard"] = rec["shard"]
            if rec.get("pid"):
                # the identity labels ride every sample so a restarted
                # shard's series never collide with its predecessor's
                inject["pid"] = str(rec["pid"])
            if rec.get("port"):
                inject["port"] = str(rec["port"])
            parts.append((inject, rec["exposition"]))
        return merge_expositions(parts)

    def summary(self, records: Optional[list[dict]] = None) -> dict:
        """/debug/fleet: topology plus health, one row per endpoint."""
        rows = []
        for rec in (records if records is not None else self.scrape()):
            rows.append({k: rec[k] for k in
                         ("component", "shard", "url", "healthy",
                          "error")}
                        | {"samples": rec.get("samples", 0),
                           "pid": rec.get("pid"),
                           "port": rec.get("port"),
                           "role": rec.get("role")})
        return {"endpoints": rows,
                "healthy": sum(1 for r in rows if r["healthy"]),
                "total": len(rows),
                "ok": all(r["healthy"] and not r["error"]
                          for r in rows)}
