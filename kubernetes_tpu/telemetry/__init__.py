"""Fleet telemetry plane: wire trace propagation, fleet-wide metrics
aggregation, and the device-launch profiler.

Three pillars (ISSUE 10, after the Kant unified-observability argument
— arXiv:2510.01256 — that large-AI-cluster schedulers need fleet-level
views, not per-component counters):

* :mod:`kubernetes_tpu.telemetry.trace` — :class:`TraceContext`, the
  compact per-commit trace stamp (origin component, commit timestamp,
  relay hop count) carried inside every :class:`JournalEvent`, threaded
  through both wire codecs and relay hops so `PodTimelines` can join
  hub/relay/scheduler/binder/kubelet-ack stamps into one end-to-end
  timeline per pod.
* :mod:`kubernetes_tpu.telemetry.fleet` — the strict exposition-format
  parser, per-component `/metrics` renderers (hub, relay, kubemark),
  and :class:`FleetView`, the collector that pulls every fabric
  component's `/metrics`+`/healthz` and merges them into one exposition
  with ``component``/``shard`` labels (`/debug/fleet`).
* :mod:`kubernetes_tpu.telemetry.profiler` — :class:`DeviceProfiler`,
  the device-launch instrument: XLA compiles per bucket shape,
  recompile attribution to re-bucket churn, per-launch walltime, and
  live device-buffer bytes (`scheduler_device_*` metrics).
"""

from kubernetes_tpu.telemetry.trace import TraceContext, new_context


def incident(sched, kind: str, reason: str = "", **details) -> None:
    """Raise one incident on ``sched``'s watchdog (telemetry/watchdog):
    the direct hook the ~8 containment sites call when they fire, so
    the black-box bundle freezes the evidence THE CYCLE the fault
    happened instead of waiting for the next maintenance poll. A no-op
    before the watchdog attaches (early init, bare test schedulers) and
    never raises — containment paths call this mid-recovery."""
    wd = getattr(sched, "watchdog", None)
    if wd is not None:
        wd.incident(kind, reason=reason, details=details or None)


__all__ = ["TraceContext", "new_context", "incident"]
