"""The telemetry CLI: ``python -m kubernetes_tpu.telemetry autopsy ...``

Offline incident forensics over an autopsy bundle directory
(config.autopsy_dir — the black boxes the SLO watchdog files):

* ``autopsy list --dir D`` — one row per bundle (seq, trigger class,
  reason, size); torn files are listed with their error.
* ``autopsy show --dir D NAME [--section S]`` — one parsed bundle (or
  one section of it), strict: a torn bundle exits non-zero.
* ``autopsy diff --dir D A B`` — stats-counter / phase-p99 / SLO-stat
  deltas between two bundles.
* ``autopsy critical-path --dir D NAME [--pod NS/NAME]`` — per-pod
  span breakdown (created → queued → popped → bound → acked) from the
  bundle's timelines, wait time attributed to queue / device / binder
  / fabric legs.
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{v:.1f}ms"


def _cmd_list(args) -> int:
    from kubernetes_tpu.telemetry.autopsy import list_bundles

    rows = list_bundles(args.dir)
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return 0
    if not rows:
        print(f"no bundles under {args.dir}")
        return 0
    for r in rows:
        if "error" in r:
            print(f"{r['name']}  UNREADABLE: {r['error']}")
            continue
        print(f"{r['name']}  seq={r['seq']} kind={r['kind']} "
              f"rule={r.get('rule') or '-'} bytes={r['bytes']}  "
              f"{r.get('reason') or ''}")
    return 0


def _load(args, name: str):
    import os

    from kubernetes_tpu.telemetry.autopsy import load_bundle

    path = name if os.sep in name else os.path.join(args.dir, name)
    return load_bundle(path)


def _cmd_show(args) -> int:
    doc = _load(args, args.name)
    if args.section:
        if args.section not in doc:
            print(f"no section {args.section!r} "
                  f"(have: {', '.join(sorted(doc))})", file=sys.stderr)
            return 1
        doc = doc[args.section]
    print(json.dumps(doc, indent=2, default=str))
    return 0


def _cmd_diff(args) -> int:
    from kubernetes_tpu.telemetry.autopsy import diff_bundles

    print(json.dumps(diff_bundles(_load(args, args.a),
                                  _load(args, args.b)),
                     indent=2, default=str))
    return 0


def _cmd_critical_path(args) -> int:
    from kubernetes_tpu.telemetry.autopsy import critical_path

    doc = _load(args, args.name)
    timelines = doc.get("timelines") or []
    if args.pod:
        timelines = [
            t for t in timelines
            if f"{t.get('namespace')}/{t.get('name')}" == args.pod
            or t.get("name") == args.pod or t.get("uid") == args.pod]
        if not timelines:
            print(f"pod {args.pod!r} not in this bundle's timelines",
                  file=sys.stderr)
            return 1
    reports = [critical_path(t) for t in timelines]
    if args.json:
        print(json.dumps(reports, indent=2, default=str))
        return 0
    for rep in reports:
        print(f"{rep['pod']}  total={_fmt_ms(rep['total_ms'])}  "
              + " ".join(f"{k}={v:.1f}ms"
                         for k, v in rep["attributed_ms"].items()))
        for leg in rep["legs"]:
            print(f"  {leg['leg']:<12} {leg['ms']:>9.3f}ms  "
                  f"[{leg['attribution']}]  "
                  f"{leg['from']} -> {leg['to']}")
        if rep["missing"]:
            print(f"  (missing legs: {', '.join(rep['missing'])})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m kubernetes_tpu.telemetry")
    sub = ap.add_subparsers(dest="cmd", required=True)
    aut = sub.add_parser("autopsy", help="incident bundle forensics")
    asub = aut.add_subparsers(dest="autopsy_cmd", required=True)

    p = asub.add_parser("list", help="list bundles in a store dir")
    p.add_argument("--dir", required=True)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_list)

    p = asub.add_parser("show", help="print one parsed bundle")
    p.add_argument("name")
    p.add_argument("--dir", default=".")
    p.add_argument("--section",
                   help="print one top-level section only")
    p.set_defaults(fn=_cmd_show)

    p = asub.add_parser("diff", help="delta between two bundles")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--dir", default=".")
    p.set_defaults(fn=_cmd_diff)

    p = asub.add_parser("critical-path",
                        help="per-pod span breakdown from a bundle")
    p.add_argument("name")
    p.add_argument("--dir", default=".")
    p.add_argument("--pod", help="ns/name, name, or uid filter")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_critical_path)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
