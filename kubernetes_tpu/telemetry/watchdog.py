"""Always-on SLO watchdog: declarative breach rules over live signals.

Runs on the scheduler's maintenance cadence (``Watchdog.poll`` at the
end of ``run_maintenance``, self-throttled to ``watchdog_interval_s``)
and evaluates a small rule set over signals the system already
produces — no new instrumentation on the hot path:

* :class:`SloRule` — live time-to-bind percentiles from
  ``PodTimelines`` (telemetry/slo.py) against ``config.watchdog_slo``.
* :class:`CounterDeltaRule` — deltas on health counters that have no
  direct containment hook: 429 sheds (``hub_client_throttled``), watch
  relists, surviving cycle crashes.
* :class:`UnattributedCompileRule` — DeviceProfiler compiles the
  bucket ladder cannot explain (the "why did that launch stall" class).
* :class:`FleetUnhealthyRule` — FleetView component health (its own
  longer cadence: a fleet scrape is live HTTP).

A trip raises an *incident*: counted per class in
``scheduler_watchdog_incidents_total`` and — when an
:class:`~kubernetes_tpu.telemetry.autopsy.AutopsyStore` is attached —
captured as a black-box bundle (rate-limited per class by the store).
Containment sites raise incidents DIRECTLY through
``telemetry.incident(sched, kind, ...)`` (device fallback, quarantine,
brownout, drift, fenced bind, hub-degraded, slice reparent): the event
is the trigger, no polling delay, the bundle freezes the evidence the
very cycle it fired.

The watchdog holds no thread and takes no locks of its own — poll()
runs under the scheduler lock like the rest of maintenance, and
``incident`` never raises (a broken autopsy must not take down the
containment path it observes).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.watchdog")

# FleetView scrapes are live HTTP across every fabric component — poll
# them far less often than the cheap in-process rules
FLEET_RULE_MIN_INTERVAL_S = 30.0


class Rule:
    """One declarative breach rule. ``evaluate`` returns a list of trip
    dicts ({"kind", "reason", "details"}); the watchdog stamps the rule
    name and routes each trip through the incident path."""

    name = "rule"
    min_interval_s = 0.0

    def evaluate(self, sched) -> list[dict]:  # pragma: no cover
        raise NotImplementedError


class SloRule(Rule):
    """Live time-to-bind stats vs the configured SLO dict. Gated on a
    minimum bound-pod count so a cold start's empty percentiles never
    breach; re-trips every poll while the breach persists (the autopsy
    store's per-class rate limit keeps the bundle count bounded)."""

    name = "slo"

    def __init__(self, slo: dict, min_binds: int = 8):
        self.slo = dict(slo)
        self.min_binds = max(0, min_binds)

    def evaluate(self, sched) -> list[dict]:
        if not self.slo:
            return []
        from kubernetes_tpu.telemetry.slo import (evaluate_slo,
                                                  time_to_bind_stats)

        stats = time_to_bind_stats(sched.timelines)
        if stats["count"] < self.min_binds:
            return []
        verdict = evaluate_slo(stats, self.slo)
        if verdict["ok"]:
            return []
        worst = verdict["breaches"][0]
        return [{"kind": "slo_breach",
                 "reason": f"{worst['metric']}={worst['value']} "
                           f"over limit {worst['limit']}",
                 "details": {"stats": stats,
                             "breaches": verdict["breaches"]}}]


class CounterDeltaRule(Rule):
    """Fires when a watched counter moved since the previous poll.
    Covers the containment signals that have NO direct incident hook
    (429 sheds happen inside the hub client, relists inside the
    informer, crashes inside the daemon wrapper) — the hooked sites
    (fallback/quarantine/brownout/drift/fence) are deliberately absent
    so one fault never double-fires."""

    def __init__(self, name: str, kind: str,
                 read: Callable[..., float]):
        self.name = name
        self.kind = kind
        self._read = read
        self._last: Optional[float] = None

    def evaluate(self, sched) -> list[dict]:
        try:
            cur = float(self._read(sched))
        except Exception:  # noqa: BLE001 — a missing counter is not an
            return []                            # incident
        last, self._last = self._last, cur
        if last is None or cur <= last:
            return []
        return [{"kind": self.kind,
                 "reason": f"{self.name} advanced by {cur - last:g} "
                           f"(now {cur:g})",
                 "details": {"counter": self.name, "delta": cur - last,
                             "value": cur}}]


class UnattributedCompileRule(Rule):
    """DeviceProfiler compiles with no attributed cause: every compile
    should be explained by first-touch, re-bucketing, gang/batch bucket
    growth, or a flags change — an unattributed one means an unknown
    recompile source is eating launch walltime."""

    name = "unattributed_compile"

    def __init__(self):
        self._last: Optional[int] = None

    def evaluate(self, sched) -> list[dict]:
        prof = getattr(sched, "profiler", None)
        if prof is None:
            return []
        cur = int(getattr(prof, "compile_causes", {})
                  .get("unattributed", 0))
        last, self._last = self._last, cur
        if last is None or cur <= last:
            return []
        return [{"kind": "unattributed_compile",
                 "reason": f"{cur - last} unattributed XLA compile(s) "
                           f"(total {cur})",
                 "details": {"delta": cur - last, "total": cur}}]


class FleetUnhealthyRule(Rule):
    """FleetView says a fabric component failed healthz or its scrape —
    the one rule that does live HTTP, so it carries its own (longer)
    minimum interval on top of the watchdog cadence."""

    name = "fleet"
    min_interval_s = FLEET_RULE_MIN_INTERVAL_S

    def evaluate(self, sched) -> list[dict]:
        fleet = getattr(sched, "fleet", None)
        if fleet is None:
            return []
        try:
            summary = fleet.summary()
        except Exception:  # noqa: BLE001 — a fleet view that cannot
            return []      # even summarize is the hub-degraded story
        if summary.get("ok", True):
            return []
        bad = [f"{e.get('component')}@{e.get('url')}"
               for e in summary.get("endpoints", [])
               if not e.get("healthy", True) or e.get("error")]
        return [{"kind": "fleet_unhealthy",
                 "reason": f"{summary.get('healthy', '?')}/"
                           f"{summary.get('total', '?')} components "
                           f"healthy",
                 "details": {"unhealthy": bad, "summary": summary}}]


def default_rules(config) -> list[Rule]:
    """The stock rule set for one scheduler config (the README's rule
    catalog). Counter reads go through the metrics registry so they see
    exactly what /metrics exports."""
    return [
        SloRule(getattr(config, "watchdog_slo", {}) or {},
                getattr(config, "watchdog_min_binds", 8)),
        CounterDeltaRule(
            "hub_client_throttled_total", "throttle_shed",
            lambda s: s.metrics.hub_client_throttled.value()),
        CounterDeltaRule(
            "hub_watch_relists_total", "watch_relist",
            lambda s: s.metrics.hub_watch_relists.value()),
        CounterDeltaRule(
            "scheduler_cycle_crashes_total", "cycle_crash",
            lambda s: s.metrics.cycle_crashes.value()),
        UnattributedCompileRule(),
        FleetUnhealthyRule(),
    ]


class Watchdog:
    """The scheduler's breach detector + incident router. Constructed
    unconditionally (it is a handful of comparisons per maintenance
    window); the autopsy store attaches only when ``autopsy_dir`` is
    configured."""

    def __init__(self, sched, rules: Optional[list[Rule]] = None,
                 store=None, interval_s: float = 5.0,
                 now: Callable[[], float] = time.time):
        self.sched = sched
        self.rules = rules if rules is not None \
            else default_rules(sched.config)
        self.store = store
        self.interval_s = max(0.0, interval_s)
        self._now = now
        self._last_poll: Optional[float] = None
        self._last_by_rule: dict[str, float] = {}
        self.incidents = 0

    def poll(self) -> int:
        """Evaluate the rule set (at most once per interval); returns
        the number of trips raised this evaluation."""
        now = self._now()
        if self._last_poll is not None \
                and now - self._last_poll < self.interval_s:
            return 0
        self._last_poll = now
        m = getattr(self.sched, "metrics", None)
        if m is not None:
            m.watchdog_evals.inc()
        trips = 0
        for rule in self.rules:
            if rule.min_interval_s:
                last = self._last_by_rule.get(rule.name)
                if last is not None \
                        and now - last < rule.min_interval_s:
                    continue
                self._last_by_rule[rule.name] = now
            try:
                hits = rule.evaluate(self.sched)
            except Exception:  # noqa: BLE001 — one broken rule must
                # not starve the rest of the set (or maintenance)
                logger.exception("watchdog rule %s raised", rule.name)
                continue
            for hit in hits:
                trips += 1
                if m is not None:
                    m.watchdog_rules_tripped.inc(rule=rule.name)
                self.incident(hit.get("kind", rule.name),
                              reason=hit.get("reason", ""),
                              rule=rule.name,
                              details=hit.get("details"))
        return trips

    def incident(self, kind: str, reason: str = "", rule: str = "",
                 details: Optional[dict] = None) -> None:
        """Raise one incident: count it, and (when a store is attached)
        capture a black-box bundle. Never raises — containment sites
        call this mid-recovery."""
        try:
            self.incidents += 1
            m = getattr(self.sched, "metrics", None)
            if m is not None:
                m.watchdog_incidents.inc(kind=kind)
            if self.store is None:
                return
            from kubernetes_tpu.telemetry.autopsy import collect_bundle

            trigger = {"kind": kind, "reason": reason, "rule": rule}
            if details is not None:
                trigger["details"] = details
            self.store.capture(
                trigger, lambda: collect_bundle(self.sched, trigger))
        except Exception:  # noqa: BLE001 — observability must not
            logger.exception("incident handling failed (%s)", kind)
