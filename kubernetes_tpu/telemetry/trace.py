"""Cross-wire trace context: one compact stamp per committed mutation.

Every hub ``_commit`` stamps the :class:`JournalEvent` it journals with a
:class:`TraceContext` — origin component (``"hub"`` or the fabric shard
name), the commit wall-clock timestamp, and a relay hop count. The stamp
travels with the event through both wire codecs (a registered wire
dataclass: positional on ``bin1``, a tagged dict on the JSON fallback —
a JSON-era middlebox like the chaos proxy passes it through untouched
because it lives INSIDE the event body, not in a header) and through
relay hops, each relay incrementing ``hops`` as it fans the event out.

Degradation contract: a peer or path that cannot carry the context (a
pre-telemetry server, a relay state-mirror LIST replay — mirrors keep
objects, not events) delivers the event with ``trace=None``. Hop data
degrades; events are never dropped or withheld over missing telemetry.

Clock note: ``ts`` is ``time.time()`` (wall clock), not a monotonic
reading — the stamp's whole purpose is to be compared against OTHER
components' stamps (scheduler cycle stamps, kubelet acks), and
monotonic clocks are not comparable across processes. Within one host
(every deployment this repo drives) wall-clock deltas between
components are exact; across hosts they are NTP-grade, same as the
reference's Event timestamps.

``joined_latency`` is the read side: given one pod's ``/debug/pod``
timeline (PodTimelines.get), it reduces the wire stamps into the
end-to-end created -> bound -> acked latencies the ``--fanout-smoke``
SLO gate aggregates into a p99.
"""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass(frozen=True)
class TraceContext:
    """One commit's trace stamp. Frozen — a relay NEVER mutates the
    stamp it received; ``hop()`` derives the next hop's copy."""

    origin: str = ""      # committing component ("hub", "pods-2", ...)
    ts: float = 0.0       # commit wall-clock stamp (time.time())
    hops: int = 0         # relay hops crossed since the commit

    def hop(self) -> "TraceContext":
        """The stamp one relay hop downstream."""
        return TraceContext(self.origin, self.ts, self.hops + 1)


def new_context(origin: str) -> TraceContext:
    return TraceContext(origin=origin, ts=time.time(), hops=0)


# the wire stamps a complete end-to-end pod trace joins (PodTimelines
# "wire" dict keys): created = the pod's hub add commit, bound = the
# bind's hub commit, acked = the kubelet status-Running commit;
# kubelet_recv (optional) = the bound event's arrival at the kubelet
# after its relay hops, threaded back through the ack's annotation
JOIN_REQUIRED = ("created", "bound", "acked")

# annotation the kubelet ack carries its received bind-event trace in
# (the baggage header of this wire): "hops@ts@origin"
ACK_TRACE_ANNOTATION = "telemetry.ktpu.io/ack-trace"


def format_ack_trace(tr: TraceContext) -> str:
    return f"{tr.hops}@{tr.ts:.6f}@{tr.origin}"


def parse_ack_trace(value: str) -> TraceContext | None:
    try:
        hops, ts, origin = value.split("@", 2)
        return TraceContext(origin=origin, ts=float(ts), hops=int(hops))
    except (ValueError, AttributeError):
        return None     # malformed baggage degrades, never raises


def joined_latency(timeline: dict | None) -> dict | None:
    """Reduce one pod timeline's wire stamps to the joined end-to-end
    latencies. Returns None when the timeline is missing or incomplete
    (one of ``JOIN_REQUIRED`` absent — the pod is not "joinable")."""
    if not timeline:
        return None
    wire = timeline.get("wire") or {}
    if any(k not in wire for k in JOIN_REQUIRED):
        return None
    created, bound, acked = (wire[k]["t"] for k in JOIN_REQUIRED)
    out = {
        "created_ts": round(created, 6),
        "create_to_bind_s": round(bound - created, 6),
        "create_to_ack_s": round(acked - created, 6),
        "bind_to_ack_s": round(acked - bound, 6),
        "relay_hops": max(int(s.get("hops", 0)) for s in wire.values()),
    }
    kr = wire.get("kubelet_recv")
    if kr is not None:
        out["bind_to_kubelet_s"] = round(kr["t"] - bound, 6)
    return out


def latency_summary(latencies: list[float]) -> dict:
    """p50/p99/max over joined latencies (exact-sample percentiles, the
    --fanout-smoke SLO report)."""
    if not latencies:
        return {"count": 0}
    xs = sorted(latencies)

    def pct(q: float) -> float:
        return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]

    return {"count": len(xs),
            "p50_s": round(pct(50), 6),
            "p99_s": round(pct(99), 6),
            "max_s": round(xs[-1], 6)}
