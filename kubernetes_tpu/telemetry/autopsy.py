"""Incident autopsy: bounded black-box bundles + per-pod critical path.

When the SLO watchdog (telemetry/watchdog.py) raises an incident — a
rule trip on the maintenance cadence or a containment site firing
directly through ``telemetry.incident(...)`` — the evidence that
explains it is about to evaporate: the flight-recorder ring rolls over,
the journal suffix advances, /debug surfaces show only the present.
The :class:`AutopsyStore` freezes that evidence to disk as ONE atomic
JSON bundle per incident:

* the flight-recorder ring suffix + phase percentiles + occupancy,
* the last-K pod timelines (events, wire stamps, joined latency),
* the hub journal's ``list_changes`` suffix,
* queue / gang / job-queue debug snapshots + the stats dict,
* the DeviceProfiler compile-event ring,
* a FleetView scrape (when a fleet view is attached),
* live time-to-bind stats and the trigger rule + metric values.

Bounded by construction: retention caps on bundle count AND total
bytes (oldest pruned first), per-incident-class rate limiting so a
storm of identical faults files one bundle per window, and atomic
tmp+``os.replace`` writes so a reader never sees a torn bundle (a
killed writer leaves only a ``.tmp`` the reader skips).

The offline half lives here too: ``list_bundles`` / ``load_bundle``
(torn-tolerant), ``diff_bundles``, and ``critical_path`` — the per-pod
span breakdown (created → enqueued → popped → bound → acked) that
attributes wait time to the queue, device+commit, binder/hub, and
fabric legs from the timeline + wire stamps already in every bundle.
``python -m kubernetes_tpu.telemetry autopsy ...`` fronts them.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.autopsy")

BUNDLE_FORMAT = 1
BUNDLE_PREFIX = "autopsy-"
BUNDLE_SUFFIX = ".json"

# bundle bounds (per capture): ring/timeline/journal suffix sizes. The
# point is a BOUNDED black box — enough tail to reconstruct the minutes
# before the trigger, never the whole history.
RING_SUFFIX_CYCLES = 32
TIMELINE_SUFFIX_PODS = 16
JOURNAL_SUFFIX_EVENTS = 128
PROFILER_SUFFIX_EVENTS = 32


def _slug(s: str) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "-"
                  for c in (s or "incident").lower())
    return out[:48] or "incident"


class AutopsyStore:
    """Bounded on-disk bundle store: atomic writes, per-class rate
    limiting, count+bytes retention. Thread-safe (containment sites and
    the maintenance poll may race on a storm)."""

    def __init__(self, directory: str, max_bundles: int = 32,
                 max_bytes: int = 16 * 1024 * 1024,
                 rate_limit_s: float = 30.0,
                 now: Callable[[], float] = time.time,
                 metrics=None):
        self.directory = directory
        self.max_bundles = max(1, max_bundles)
        self.max_bytes = max(4096, max_bytes)
        self.rate_limit_s = max(0.0, rate_limit_s)
        self._now = now
        self._metrics = metrics
        self._lock = threading.Lock()
        self._last_by_kind: dict[str, float] = {}
        os.makedirs(directory, exist_ok=True)
        # resume the sequence after a restart so retention ordering
        # (oldest-first pruning) survives the process
        self._seq = 0
        for name in self._names():
            try:
                self._seq = max(self._seq,
                                int(name[len(BUNDLE_PREFIX):].split("-")[0]))
            except (ValueError, IndexError):
                continue

    # ------------- capture -------------

    def capture(self, trigger: dict,
                collect: Callable[[], dict]) -> Optional[str]:
        """File one bundle for ``trigger`` (a dict with at least
        ``kind``). ``collect`` is called ONLY after the rate-limit gate
        admits the class — a storm of identical incidents costs one
        bundle (and one collection walk) per window. Returns the bundle
        path, or None when rate-limited or the write failed."""
        kind = str(trigger.get("kind", "incident"))
        now = self._now()
        with self._lock:
            last = self._last_by_kind.get(kind)
            if last is not None and self.rate_limit_s > 0 \
                    and now - last < self.rate_limit_s:
                self._drop("rate_limited")
                return None
            self._last_by_kind[kind] = now
            self._seq += 1
            seq = self._seq
        try:
            body = collect()
        except Exception:  # noqa: BLE001 — the autopsy must never take
            # down the path it is observing; a failed collection still
            # files the trigger so the incident is not silently lost
            logger.exception("autopsy collection failed for %s", kind)
            body = {"collect_errors": ["collection raised; "
                                       "trigger-only bundle"]}
        doc = {"format": BUNDLE_FORMAT, "seq": seq,
               "captured_at": round(now, 6), "trigger": trigger}
        doc.update(body)
        name = f"{BUNDLE_PREFIX}{seq:06d}-{_slug(kind)}{BUNDLE_SUFFIX}"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            logger.exception("autopsy bundle write failed: %s", path)
            self._drop("write_error")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        if self._metrics is not None:
            self._metrics.autopsy_bundles.inc(trigger=_slug(kind))
        self._prune()
        return path

    def _drop(self, reason: str) -> None:
        if self._metrics is not None:
            self._metrics.autopsy_bundles_dropped.inc(reason=reason)

    def _names(self) -> list[str]:
        try:
            return sorted(n for n in os.listdir(self.directory)
                          if n.startswith(BUNDLE_PREFIX)
                          and n.endswith(BUNDLE_SUFFIX))
        except OSError:
            return []

    def _prune(self) -> None:
        """Retention: newest max_bundles bundles / max_bytes total.
        Lexicographic name order IS seq order (zero-padded)."""
        with self._lock:
            names = self._names()
            sizes = {}
            for n in names:
                try:
                    sizes[n] = os.path.getsize(
                        os.path.join(self.directory, n))
                except OSError:
                    sizes[n] = 0
            total = sum(sizes.values())
            while names and (len(names) > self.max_bundles
                             or total > self.max_bytes):
                victim = names.pop(0)
                try:
                    os.unlink(os.path.join(self.directory, victim))
                except OSError:
                    pass
                total -= sizes.get(victim, 0)
                self._drop("retention")
            if self._metrics is not None:
                self._metrics.autopsy_store_bytes.set(float(total))

    # ------------- reading (also /debug/autopsy) -------------

    def list(self) -> list[dict]:
        return list_bundles(self.directory)

    def load(self, name: str) -> dict:
        return load_bundle(os.path.join(self.directory, name))


# ------------- collection (called on the scheduler's thread) -------------


def collect_bundle(sched, trigger: dict) -> dict:
    """Walk the scheduler's live debug surfaces into one bundle body.
    Every section is individually guarded: a down hub or detached fleet
    view yields a partial bundle with the failure named in
    ``collect_errors``, never a lost incident."""
    body: dict = {}
    errors: list[str] = []

    def section(name: str, fn):
        try:
            v = fn()
            if v is not None:
                body[name] = v
        except Exception as e:  # noqa: BLE001 — partial bundles beat
            errors.append(f"{name}: {e!r}")       # lost incidents

    flight = getattr(sched, "flight", None)
    if flight is not None:
        section("flight", lambda: {
            "cycles": flight.last(RING_SUFFIX_CYCLES),
            "phases": flight.phase_percentiles(),
            "host_tail_share": round(flight.host_tail_share(), 4),
            "occupancy": flight.occupancy_stats(),
        })
    timelines = getattr(sched, "timelines", None)
    if timelines is not None:
        def _timelines():
            uids = timelines.uids()[-TIMELINE_SUFFIX_PODS:]
            return [t for t in (timelines.get(uid=u) for u in uids)
                    if t is not None]
        section("timelines", _timelines)

        def _slo_stats():
            from kubernetes_tpu.telemetry.slo import time_to_bind_stats
            return time_to_bind_stats(timelines)
        section("slo_stats", _slo_stats)
    section("queue", lambda: {
        "pending": sched.queue.pending_counts(),
        "stats": dict(sched.stats),
    })
    gang = getattr(sched, "_gang", None)
    if gang is not None:
        section("gangs", gang.debug_state)
    jq = getattr(sched, "jobqueue", None)
    if jq is not None and getattr(jq, "active", False):
        section("job_queue", jq.debug_state)
    prof = getattr(sched, "profiler", None)
    if prof is not None:
        section("profiler",
                lambda: prof.snapshot(events=PROFILER_SUFFIX_EVENTS))
    bs_fn = getattr(sched, "brownout_state", None)
    if bs_fn is not None:
        section("brownout", bs_fn)
    fleet = getattr(sched, "fleet", None)
    if fleet is not None:
        section("fleet", fleet.summary)

    def _journal():
        js_fn = getattr(sched.hub, "get_journal_stats", None)
        lc_fn = getattr(sched.hub, "list_changes", None)
        if js_fn is None or lc_fn is None:
            return None
        rv = int(js_fn().get("rv", 0) or 0)
        since = max(0, rv - JOURNAL_SUFFIX_EVENTS)
        res = lc_fn(since)
        return {"rv": res.get("rv"), "since": since,
                "too_old": res.get("too_old", False),
                "changes": [
                    {"rv": c.get("rv"), "kind": c.get("kind"),
                     "type": c.get("type"),
                     "name": getattr(getattr(c.get("obj"), "metadata",
                                             None), "name", None)}
                    for c in res.get("changes", [])]}
    section("journal", _journal)
    if errors:
        body["collect_errors"] = errors
    return body


# ------------- offline readers (CLI + tests) -------------


def list_bundles(directory: str) -> list[dict]:
    """One summary row per bundle, oldest first. Torn/unparseable files
    are listed with an ``error`` field instead of aborting the listing
    (a kill -9 mid-replace leaves at worst a ``.tmp`` we never match)."""
    rows = []
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith(BUNDLE_PREFIX)
                       and n.endswith(BUNDLE_SUFFIX))
    except OSError:
        return rows
    for name in names:
        path = os.path.join(directory, name)
        row: dict = {"name": name}
        try:
            row["bytes"] = os.path.getsize(path)
            doc = load_bundle(path)
            trig = doc.get("trigger", {})
            row.update({
                "seq": doc.get("seq"),
                "captured_at": doc.get("captured_at"),
                "kind": trig.get("kind"),
                "rule": trig.get("rule"),
                "reason": trig.get("reason"),
            })
        except (OSError, ValueError) as e:
            row["error"] = str(e)
        rows.append(row)
    return rows


def load_bundle(path: str) -> dict:
    """Parse one bundle strictly; raises ValueError on torn/invalid
    files (the CLI turns that into a non-zero exit — a bundle that does
    not parse is itself an incident)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise ValueError(f"torn or invalid bundle {path}: {e}") from e
    if not isinstance(doc, dict) or "trigger" not in doc:
        raise ValueError(f"not an autopsy bundle: {path}")
    if int(doc.get("format", 0)) > BUNDLE_FORMAT:
        raise ValueError(
            f"bundle format {doc.get('format')} is newer than this "
            f"reader ({BUNDLE_FORMAT}): {path}")
    return doc


def diff_bundles(a: dict, b: dict) -> dict:
    """What changed between two bundles: stats-counter deltas, phase
    p99 shifts, SLO stat movement, and the trigger pair. The operator
    question it answers: what did the system DO between these two
    incidents."""
    out: dict = {
        "a": {"seq": a.get("seq"), "kind":
              a.get("trigger", {}).get("kind")},
        "b": {"seq": b.get("seq"), "kind":
              b.get("trigger", {}).get("kind")},
        "seconds_apart": round((b.get("captured_at") or 0)
                               - (a.get("captured_at") or 0), 3),
    }
    sa = (a.get("queue") or {}).get("stats") or {}
    sb = (b.get("queue") or {}).get("stats") or {}
    deltas = {}
    for k in sorted(set(sa) | set(sb)):
        va, vb = sa.get(k, 0), sb.get(k, 0)
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
                and vb != va:
            deltas[k] = vb - va
    out["stats_delta"] = deltas
    pa = (a.get("flight") or {}).get("phases") or {}
    pb = (b.get("flight") or {}).get("phases") or {}
    phases = {}
    for ph in sorted(set(pa) | set(pb)):
        p99a = (pa.get(ph) or {}).get("p99_ms")
        p99b = (pb.get(ph) or {}).get("p99_ms")
        if p99a != p99b:
            phases[ph] = {"p99_ms_a": p99a, "p99_ms_b": p99b}
    out["phase_p99_delta"] = phases
    slo_a = a.get("slo_stats") or {}
    slo_b = b.get("slo_stats") or {}
    out["slo_delta"] = {
        k: {"a": slo_a.get(k), "b": slo_b.get(k)}
        for k in sorted(set(slo_a) | set(slo_b))
        if slo_a.get(k) != slo_b.get(k)}
    return out


# the per-pod span legs, in lifecycle order: (leg name, from-stamp,
# to-stamp, attribution). Stamps resolve against the merged event/wire
# map built by critical_path; absent stamps skip the leg.
_CRITICAL_LEGS = (
    ("watch", "wire:created", "enqueued", "fabric"),
    ("queue", "enqueued", "popped:first", "queue"),
    ("retries", "popped:first", "popped:last", "queue"),
    ("schedule", "popped:last", "bound", "device"),
    ("hub_commit", "bound", "wire:bound", "binder"),
    ("fabric_relay", "wire:bound", "wire:kubelet_recv", "fabric"),
    ("kubelet_ack", "wire:kubelet_recv", "wire:acked", "fabric"),
)


def critical_path(timeline: dict) -> dict:
    """Per-pod span breakdown from one timeline record (as stored in
    bundles / returned by ``PodTimelines.get``): created → watched →
    queued → popped → bound → acked, with each wait attributed to the
    queue, device (schedule+commit), binder (hub write), or fabric
    (relay + kubelet) leg. Missing stamps (pod never bound, wire trace
    disabled) skip their legs and are named in ``missing``."""
    stamps: dict[str, float] = {}
    for ev in timeline.get("events", []):
        t, name = ev.get("t"), ev.get("event")
        if t is None or not name:
            continue
        if name == "popped":
            stamps.setdefault("popped:first", t)
            stamps["popped:last"] = t
        else:
            stamps.setdefault(name, t)
    for stamp, rec in (timeline.get("wire") or {}).items():
        t = rec.get("t") if isinstance(rec, dict) else None
        if t is not None:
            stamps.setdefault(f"wire:{stamp}", t)
    legs, missing = [], []
    attributed: dict[str, float] = {}
    for leg, frm, to, attr in _CRITICAL_LEGS:
        t0, t1 = stamps.get(frm), stamps.get(to)
        if t0 is None or t1 is None:
            missing.append(leg)
            continue
        ms = max(0.0, (t1 - t0) * 1e3)
        legs.append({"leg": leg, "from": frm, "to": to,
                     "ms": round(ms, 3), "attribution": attr})
        attributed[attr] = attributed.get(attr, 0.0) + ms
    first = stamps.get("wire:created", stamps.get("enqueued"))
    last_candidates = [stamps[k] for k in
                       ("wire:acked", "wire:kubelet_recv", "wire:bound",
                        "bound") if k in stamps]
    total_ms = (round((last_candidates[0] - first) * 1e3, 3)
                if first is not None and last_candidates else None)
    return {
        "pod": f"{timeline.get('namespace', '?')}/"
               f"{timeline.get('name', '?')}",
        "uid": timeline.get("uid"),
        "legs": legs,
        "attributed_ms": {k: round(v, 3)
                          for k, v in sorted(attributed.items())},
        "total_ms": total_ms,
        "missing": missing,
    }
