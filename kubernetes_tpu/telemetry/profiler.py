"""DeviceProfiler: XLA-compile / launch-walltime / HBM-footprint meter.

Why the device path stalls is exactly what the flight recorder's phase
timings cannot say: ``device_launch`` covers compile time, queue wait,
and execution indistinguishably. This instrument attributes it:

* **Compiles per bucket shape.** Every launch computes its *shape key*
  (batch bucket, node/pod capacity buckets, topology domain bucket,
  group bucket, commit mode, optional-term flags). The jitted entry
  point's executable-cache size (``pipeline.launch_cache_size()``) is
  read after each launch: growth = one real XLA compile, attributed to
  this launch's shape and to the TRANSITION from the previous shape —
  re-bucket churn (a capacity field doubled) vs batch-bucket drift vs a
  flag flip. A compile whose shape was already seen is counted
  ``unattributed`` — the signal that something OUTSIDE the tracked key
  is forcing recompiles.
* **Per-launch walltime** per shape (count/total/max), so "one shape is
  slow" and "one shape keeps recompiling" read differently.
* **Live buffer bytes** — the HBM footprint of what the scheduler keeps
  resident: the nodes×resources cluster tensors, the per-batch pod
  tensors, the dense DRA inventories, the learned-scorer params
  (``.nbytes`` over the pytrees; metadata reads, no device sync).

Surfaced as ``scheduler_device_*`` metrics, the ``device_compile``
flight-recorder view phase (a compiling launch's walltime, double-
counted next to ``device_launch`` on purpose — the attribution view
discipline from the DRA phases), and the ``--profile`` device column.
"""

from __future__ import annotations

from typing import Optional

# the capacity fields whose growth is re-bucket churn (mirror._grow
# doubles one of these and rebuilds; kernels recompile once per bucket)
_CAP_FIELDS = ("nodes", "pods", "pod_labels", "node_labels", "domains",
               "ext_resources", "domain_cap")


def shape_key(caps, b_bucket: int, enable_topology: bool, d_cap,
              g_cap: int, serial_scan: bool, dra: bool, learned: bool,
              with_feats: bool, gang: int = 0,
              alts: bool = False, soft: bool = False) -> tuple:
    """The launch's compile-relevant shape: static jit args + input
    shape buckets, as a flat hashable tuple. ``gang`` is the gang-pack
    launch's gang-row bucket (0 for the normal scheduling launch) — a
    gang-shape recompile attributes to its own row instead of landing
    in "unattributed". ``alts`` is the with_alts static flag (the
    export v3 top-K candidate kernels); ``soft`` is the topo_soft
    static flag (the reduced soft-topology program, ISSUE 15)."""
    cap_t = tuple((f, getattr(caps, f)) for f in _CAP_FIELDS
                  if hasattr(caps, f))
    return (("b", b_bucket), ("topo", bool(enable_topology)),
            ("d_cap", d_cap), ("g_cap", g_cap),
            ("serial", bool(serial_scan)), ("dra", bool(dra)),
            ("learned", bool(learned)), ("feats", bool(with_feats)),
            ("gang", gang), ("alts", bool(alts)), ("soft", bool(soft)),
            *cap_t)


def _diff_cause(prev: Optional[tuple], cur: tuple) -> str:
    """Attribute a compile to what changed since the previous launch."""
    if prev is None:
        return "first"
    changed = {k for (k, v) in cur} - {k for (k, v) in prev}
    changed |= {k for (k, v) in cur if dict(prev).get(k) != v}
    if changed & set(_CAP_FIELDS):
        return "rebucket"                 # capacity growth recompile
    if "gang" in changed:
        return "gang"                     # gang-pack bucket transition
    if "b" in changed:
        return "batch_bucket"             # pod-batch bucket transition
    if changed & {"topo", "d_cap", "g_cap"}:
        return "topology_bucket"
    if changed:
        return "flags"                    # dra/learned/feats/commit mode
    return "unattributed"                 # same shape, cache still grew


class DeviceProfiler:
    """Per-scheduler launch profiler. Single-threaded like the flight
    recorder (note_launch runs on the scheduling-loop thread only);
    readers (`/debug/trace`, bench --profile) take cheap snapshots."""

    MAX_COMPILE_EVENTS = 256              # bounded ring discipline (PR 4)

    def __init__(self, metrics=None, cache_size_fn=None,
                 now=None):
        import time

        if cache_size_fn is None:
            from kubernetes_tpu.models.pipeline import launch_cache_size
            cache_size_fn = launch_cache_size
        self._cache_size_fn = cache_size_fn
        self._metrics = metrics
        self._now = now or time.time
        # baseline BEFORE any of this scheduler's launches: warm cache
        # entries from an earlier run in this process are not ours
        self._last_cache: Optional[int] = cache_size_fn()
        self._last_shape: Optional[tuple] = None
        self.launches = 0
        self.compiles = 0
        self.compile_causes: dict[str, int] = {}
        self.compile_events: list[dict] = []   # ring, newest last
        # shape -> {"launches", "compiles", "walltime_s", "max_s"}
        self.shapes: dict[tuple, dict] = {}
        self.buffer_bytes: dict[str, int] = {}

    # ------------- recording (loop thread) -------------

    def note_launch(self, shape: tuple) -> bool:
        """Record one dispatched launch; returns True when the jit
        executable cache grew (a real XLA compile happened while
        tracing this launch)."""
        self.launches += 1
        rec = self.shapes.get(shape)
        first_of_shape = rec is None
        if rec is None:
            rec = self.shapes[shape] = {"launches": 0, "compiles": 0,
                                        "walltime_s": 0.0, "max_s": 0.0}
        rec["launches"] += 1
        cache = self._cache_size_fn()
        compiled = (cache is not None and self._last_cache is not None
                    and cache > self._last_cache)
        if compiled:
            # a NEW shape's compile attributes to the transition that
            # produced it (re-bucket / batch bucket / flags); a compile
            # while RE-launching a known shape means something outside
            # the tracked key changed — surfaced as "unattributed", the
            # regression signal the MixedChurn acceptance gate reads
            cause = _diff_cause(self._last_shape, shape) \
                if first_of_shape else "unattributed"
            self.compiles += 1
            rec["compiles"] += 1
            self.compile_causes[cause] = \
                self.compile_causes.get(cause, 0) + 1
            self.compile_events.append({
                "at": self._now(), "cause": cause,
                "shape": dict(shape),
                "from": dict(self._last_shape)
                if self._last_shape else None})
            del self.compile_events[:-self.MAX_COMPILE_EVENTS]
            if self._metrics is not None:
                self._metrics.device_compiles.inc(cause=cause)
        if cache is not None:
            self._last_cache = cache
        self._last_shape = shape
        if self._metrics is not None:
            self._metrics.device_launch_shapes.set(
                float(len(self.shapes)))
        return compiled

    def observe_walltime(self, shape: tuple, secs: float) -> None:
        rec = self.shapes.get(shape)
        if rec is not None:
            rec["walltime_s"] += secs
            rec["max_s"] = max(rec["max_s"], secs)

    def note_buffers(self, buffers: dict[str, int]) -> None:
        """Record the live device-buffer footprint by buffer family
        (cluster / pods / dra / learned), bytes."""
        self.buffer_bytes = dict(buffers)
        if self._metrics is not None:
            for name, nbytes in buffers.items():
                self._metrics.device_live_buffer_bytes.set(
                    float(nbytes), buffer=name)

    # ------------- reading -------------

    def snapshot(self, events: int = 16) -> dict:
        """The /debug + --profile payload."""
        def label(shape: tuple) -> str:
            d = dict(shape)
            base = (f"b={d.get('b')} nodes={d.get('nodes')} "
                    f"pods={d.get('pods')} topo={int(d.get('topo', 0))} "
                    f"dra={int(d.get('dra', 0))}")
            gang = d.get("gang", 0)
            return f"{base} gang={gang}" if gang else base

        return {
            "launches": self.launches,
            "compiles": self.compiles,
            "compile_causes": dict(self.compile_causes),
            "unattributed_compiles":
                self.compile_causes.get("unattributed", 0),
            "shapes": [
                {"shape": label(s), **rec,
                 "walltime_s": round(rec["walltime_s"], 4),
                 "max_s": round(rec["max_s"], 4)}
                for s, rec in self.shapes.items()],
            "buffer_bytes": dict(self.buffer_bytes),
            "buffer_total_mib": round(
                sum(self.buffer_bytes.values()) / (1 << 20), 2),
            "recent_compiles": self.compile_events[-max(0, events):],
        }


def tree_nbytes(tree) -> int:
    """Total .nbytes over a pytree's array leaves (metadata only — no
    device sync, no transfer)."""
    if tree is None:
        return 0
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total
