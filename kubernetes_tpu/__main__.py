"""The scheduler binary: ``python -m kubernetes_tpu``.

Equivalent of cmd/kube-scheduler (app/server.go:89 Setup + Run): load the
component config, stand up the hub + scheduler + serving endpoints, run
the daemon under optional leader election until interrupted. The
in-process hub doubles as the demo API surface; a real deployment would
swap it for an apiserver-backed client implementing the same interface.
"""

from __future__ import annotations

import argparse
import signal
import socket
import sys
import threading
import uuid


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubernetes-tpu-scheduler")
    parser.add_argument("--config", help="component config file (JSON/YAML)")
    parser.add_argument("--hub", default=None,
                        help="remote hub URL (http://host:port); default "
                             "is an in-process demo hub")
    parser.add_argument("--bind-address", default="127.0.0.1")
    parser.add_argument("--secure-port", type=int, default=10259,
                        help="serving port for /metrics,/healthz,/configz "
                             "(0 = disabled)")
    parser.add_argument("--debug-token", default=None,
                        help="bearer token admitting /debug endpoints "
                             "(unset = /debug disabled, per the "
                             "reference's authz-gated debugging handlers)")
    parser.add_argument("--wal", default=None,
                        help="WAL file for the in-process hub's event "
                             "journal (restart replays it); with "
                             "--hub-shards, a WAL DIRECTORY (one file "
                             "per shard); ignored with --hub")
    parser.add_argument("--hub-shards", type=int, default=0,
                        help="shard the in-process hub (fabric."
                             "sharded.ShardedHub) with N pod shards "
                             "(0 = single hub); ignored with --hub")
    parser.add_argument("--fabric", type=int, default=0,
                        help="spawn the OUT-OF-PROCESS control-plane "
                             "fabric with N pod-shard processes (plus "
                             "the shared-state shard, nodes/events/"
                             "meta shards, and a stateless router, "
                             "each its own OS process; fabric."
                             "supervisor); the scheduler connects "
                             "through the router. --wal names the "
                             "shard WAL directory (bin1 codec). "
                             "Ignored with --hub")
    parser.add_argument("--fabric-wal-codec", default="bin1",
                        choices=("json", "bin1"),
                        help="journal WAL codec for --fabric shard "
                             "processes (bin1 ≈ 6x smaller replay)")
    parser.add_argument("--state-replicas", type=int, default=1,
                        help="with --fabric: run the shared-state core "
                             "as an N-member replicated quorum (3 = "
                             "the etcd model; leader kill -9 fails "
                             "over without losing rv/fencing/ring "
                             "state)")
    parser.add_argument("--journal-capacity", type=int, default=16384,
                        help="event-journal ring capacity per resource "
                             "kind (the watch-resume window)")
    parser.add_argument("--trace-export", default=None,
                        help="append each scheduling cycle's flight-"
                             "recorder trace as a JSON line to this file "
                             "(offline phase analysis)")
    parser.add_argument("--trace-export-learn", action="store_true",
                        help="with --trace-export: also export each "
                             "placement's feature vector AND top-K "
                             "alternative scores (the learn-loop "
                             "daemon's training + regret substrate)")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--leader-elect-lease-duration", type=float,
                        default=15.0)
    parser.add_argument("--id", default=None,
                        help="leader election identity")
    parser.add_argument("--slices", action="store_true",
                        help="horizontal scale-out: join the scheduler-"
                             "replica slice ring and drain only pods "
                             "whose namespace hashes into this "
                             "replica's owned slices (run N such "
                             "processes against one --hub; supersedes "
                             "--leader-elect)")
    parser.add_argument("--slice-heartbeat", type=float, default=2.0,
                        help="with --slices: registry heartbeat period "
                             "seconds (the TTL is 5x this, floor 10s)")
    parser.add_argument("--feature-gates", default="",
                        help="comma-separated gate=bool overrides")
    parser.add_argument("--fleet-endpoint", action="append", default=[],
                        metavar="COMPONENT[/SHARD]=URL",
                        help="register a fabric component with the "
                             "fleet collector (repeatable); serves the "
                             "merged exposition at /metrics/fleet and "
                             "the health summary at /debug/fleet")
    parser.add_argument("--validate-only", action="store_true",
                        help="load + validate the config, then exit")
    args = parser.parse_args(argv)

    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup()

    from kubernetes_tpu.config.load import load_config
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.config.validation import validate_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.plugins.registry import in_tree_registry
    from kubernetes_tpu.scheduler import Scheduler

    cfg = load_config(args.config) if args.config else default_config()
    if args.trace_export:
        cfg.trace_export_path = args.trace_export
        if args.trace_export_learn:
            cfg.trace_export_features = True
            cfg.trace_export_alts = True
    for part in filter(None, args.feature_gates.split(",")):
        name, _, val = part.partition("=")
        cfg.feature_gates[name.strip()] = val.strip().lower() in (
            "1", "true", "yes", "")
    errs = validate_config(cfg, in_tree_registry())
    if errs:
        for e in errs:
            print(f"invalid configuration: {e}", file=sys.stderr)
        return 1
    if args.validate_only:
        print("configuration valid")
        return 0

    fabric_cluster = None
    if args.hub:
        # the kubemark/hubserver deployment shape: this process holds no
        # state, it list/watches a hub in another process and rides the
        # hub-client resilience machinery through its outages
        from kubernetes_tpu.hubclient import RemoteHub

        hub = RemoteHub(args.hub)
        print(f"using remote hub {args.hub}", file=sys.stderr)
    elif args.fabric > 0:
        # process-mode fabric: every shard its own OS process with its
        # own WAL and port, a stateless router in front; this process
        # is a pure client of the router (kill -9 a shard and watch
        # the supervisor + WAL replay + re-registration heal it)
        from kubernetes_tpu.fabric.supervisor import spawn_local_cluster
        from kubernetes_tpu.hubclient import RemoteHub

        fabric_cluster = spawn_local_cluster(
            pod_shards=args.fabric, wal_dir=args.wal,
            journal_capacity=args.journal_capacity,
            wal_codec=args.fabric_wal_codec,
            state_replicas=args.state_replicas)
        hub = RemoteHub(fabric_cluster.router_url)
        print(f"fabric: {args.fabric} pod-shard processes + state/"
              f"nodes/events/meta + router at "
              f"{fabric_cluster.router_url}", file=sys.stderr)
    elif args.hub_shards > 0:
        from kubernetes_tpu.fabric.sharded import ShardedHub

        hub = ShardedHub(pod_shards=args.hub_shards,
                         journal_capacity=args.journal_capacity,
                         wal_dir=args.wal)
        print(f"sharded hub: {args.hub_shards} pod shards + "
              f"nodes/events/meta (rv={hub.current_rv})",
              file=sys.stderr)
    else:
        hub = Hub(journal_capacity=args.journal_capacity,
                  wal_path=args.wal)
        if args.wal:
            print(f"hub journal WAL at {args.wal} "
                  f"(replayed rv={hub.current_rv})", file=sys.stderr)
    sched = Scheduler(hub, cfg)

    if args.fleet_endpoint:
        from kubernetes_tpu.telemetry.fleet import FleetView

        endpoints = []
        for spec in args.fleet_endpoint:
            name, _, url = spec.partition("=")
            if not url:
                print(f"bad --fleet-endpoint {spec!r} (want "
                      "COMPONENT[/SHARD]=URL)", file=sys.stderr)
                return 1
            component, _, shard = name.partition("/")
            endpoints.append({"component": component, "shard": shard,
                              "url": url})
        sched.fleet = FleetView(endpoints)
        print(f"fleet view over {len(endpoints)} endpoints "
              "(/metrics/fleet, /debug/fleet)", file=sys.stderr)

    serving = None
    if args.secure_port:
        from kubernetes_tpu.serving import ServingEndpoints, token_auth

        serving = ServingEndpoints(
            sched, host=args.bind_address, port=args.secure_port,
            debug_auth=token_auth(args.debug_token)
            if args.debug_token else None)
        serving.start()
        print(f"serving /metrics,/healthz,/configz on "
              f"{args.bind_address}:{serving.port}", file=sys.stderr)

    elector = None
    if args.slices:
        from kubernetes_tpu.leaderelection import SliceManager

        identity = args.id or f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        url = (f"http://{args.bind_address}:{serving.port}"
               if serving is not None else "")
        elector = SliceManager(
            hub, identity, url=url,
            heartbeat_s=args.slice_heartbeat,
            ttl_s=max(10.0, 5 * args.slice_heartbeat))
        print(f"slice scale-out enabled, id={identity} "
              f"(heartbeat {args.slice_heartbeat}s)", file=sys.stderr)
    elif args.leader_elect:
        from kubernetes_tpu.leaderelection import LeaderElector

        identity = args.id or f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        elector = LeaderElector(
            hub.leases, identity,
            lease_duration=args.leader_elect_lease_duration)
        print(f"leader election enabled, id={identity}", file=sys.stderr)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())

    def _debug_dump_body() -> None:
        import json as _json

        out = _json.dumps({"cache": sched.cache.dump(),
                           "pending": sched.queue.pending_counts()},
                          default=str)
        if len(out) > 100000:
            out = out[:100000] + f'... [truncated, {len(out)} chars total]'
        print(out, file=sys.stderr)
        for line in sched.cache.compare_with_hub(hub):
            print(f"cache-vs-hub: {line}", file=sys.stderr)

    def _swallow(fn) -> None:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — diagnostics only
            try:
                print(f"cache-debugger failed: {e!r}", file=sys.stderr)
            except OSError:
                pass

    def _debug_dump(*_sig) -> None:
        """SIGUSR2 cache debugger (backend/cache/debugger/debugger.go:31):
        dump the cache and run the cache-vs-hub comparer — on its OWN
        thread, like the reference's debugger goroutine: the handler
        itself interrupts the scheduling loop mid-bytecode, where the
        RLock would let an inline dump read half-applied cache state (and
        a raising handler would crash the loop). The WHOLE handler body
        (thread start included — it can raise at the thread limit) is
        guarded: a debug signal must never take the daemon down."""
        _swallow(lambda: threading.Thread(
            target=lambda: _swallow(_debug_dump_body),
            daemon=True, name="cache-debugger").start())

    if hasattr(signal, "SIGUSR2"):
        signal.signal(signal.SIGUSR2, _debug_dump)
    print("scheduler running (ctrl-c to stop)", file=sys.stderr)
    try:
        sched.run(stop, elector=elector)
    finally:
        if serving is not None:
            serving.stop()
        sched.close()
        hub.close()   # RemoteHub: drain streams; local Hub: release WAL
        if fabric_cluster is not None:
            fabric_cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
