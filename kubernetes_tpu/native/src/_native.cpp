// kubernetes_tpu native host extension (C++/CPython C API; no pybind11).
//
// The reference's performance-critical host layer is the Go runtime itself
// (SURVEY.md §2.9); ours is XLA for the device math plus this module for
// the two host structures hot enough to show up next to it in profiles:
//
//  * KeyedHeap — the map-indexed binary heap under activeQ/backoffQ
//    (reference: pkg/scheduler/backend/heap/heap.go). Sort keys are two
//    doubles (PrioritySort = (-priority, enqueue time); backoff = expiry),
//    so sifts run entirely in C with no Python comparisons.
//  * parse_milli / parse_ceil — exact integer quantity parsing
//    (apimachinery's resource.Quantity MilliValue/Value semantics, ceil
//    rounding), replacing per-call decimal.Decimal arithmetic.
//
// Loaded by kubernetes_tpu.native (ctypes-free: a real extension module,
// compiled on first import by build()); every consumer falls back to the
// pure-Python implementation when the toolchain is unavailable.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <new>
#include <vector>

namespace {

// ------------------------------------------------------------------ heap

struct Entry {
    PyObject *key;   // owned
    double a;
    double b;
    PyObject *item;  // owned
};

struct HeapObj {
    PyObject_HEAD
    std::vector<Entry> *entries;
    PyObject *index;  // dict: key -> int position (kept in lockstep)
};

static inline bool entry_lt(const Entry &x, const Entry &y) {
    if (x.a != y.a) return x.a < y.a;
    return x.b < y.b;
}

static int heap_set_index(HeapObj *self, PyObject *key, Py_ssize_t i) {
    PyObject *pos = PyLong_FromSsize_t(i);
    if (pos == nullptr) return -1;
    int rc = PyDict_SetItem(self->index, key, pos);
    Py_DECREF(pos);
    return rc;
}

static void heap_swap(HeapObj *self, Py_ssize_t i, Py_ssize_t j) {
    auto &e = *self->entries;
    std::swap(e[i], e[j]);
    // index updates cannot fail here in practice (keys already present);
    // on the impossible failure PyErr is left set for the caller
    heap_set_index(self, e[i].key, i);
    heap_set_index(self, e[j].key, j);
}

static Py_ssize_t heap_up(HeapObj *self, Py_ssize_t i) {
    auto &e = *self->entries;
    while (i > 0) {
        Py_ssize_t parent = (i - 1) / 2;
        if (entry_lt(e[i], e[parent])) {
            heap_swap(self, i, parent);
            i = parent;
        } else {
            break;
        }
    }
    return i;
}

static void heap_down(HeapObj *self, Py_ssize_t i) {
    auto &e = *self->entries;
    Py_ssize_t n = (Py_ssize_t)e.size();
    for (;;) {
        Py_ssize_t l = 2 * i + 1, r = 2 * i + 2, smallest = i;
        if (l < n && entry_lt(e[l], e[smallest])) smallest = l;
        if (r < n && entry_lt(e[r], e[smallest])) smallest = r;
        if (smallest == i) return;
        heap_swap(self, i, smallest);
        i = smallest;
    }
}

static PyObject *heap_new(PyTypeObject *type, PyObject *, PyObject *) {
    HeapObj *self = (HeapObj *)type->tp_alloc(type, 0);
    if (self == nullptr) return nullptr;
    self->entries = new (std::nothrow) std::vector<Entry>();
    self->index = PyDict_New();
    if (self->entries == nullptr || self->index == nullptr) {
        Py_XDECREF(self->index);
        delete self->entries;
        Py_TYPE(self)->tp_free((PyObject *)self);
        return PyErr_NoMemory();
    }
    return (PyObject *)self;
}

static void heap_dealloc(HeapObj *self) {
    if (self->entries != nullptr) {
        for (Entry &e : *self->entries) {
            Py_DECREF(e.key);
            Py_DECREF(e.item);
        }
        delete self->entries;
    }
    Py_XDECREF(self->index);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *heap_add(HeapObj *self, PyObject *args) {
    PyObject *key, *item;
    double a, b;
    if (!PyArg_ParseTuple(args, "OddO", &key, &a, &b, &item)) return nullptr;
    PyObject *pos = PyDict_GetItemWithError(self->index, key);  // borrowed
    if (pos == nullptr && PyErr_Occurred()) return nullptr;
    if (pos != nullptr) {
        Py_ssize_t i = PyLong_AsSsize_t(pos);
        if (i == -1 && PyErr_Occurred()) return nullptr;
        Entry &e = (*self->entries)[i];
        Py_INCREF(key);
        Py_INCREF(item);
        Py_DECREF(e.key);
        Py_DECREF(e.item);
        e.key = key;
        e.item = item;
        e.a = a;
        e.b = b;
        heap_down(self, heap_up(self, i));
    } else {
        Py_INCREF(key);
        Py_INCREF(item);
        self->entries->push_back(Entry{key, a, b, item});
        Py_ssize_t i = (Py_ssize_t)self->entries->size() - 1;
        if (heap_set_index(self, key, i) < 0) {
            self->entries->pop_back();
            Py_DECREF(key);
            Py_DECREF(item);
            return nullptr;
        }
        heap_up(self, i);
    }
    Py_RETURN_NONE;
}

static PyObject *heap_remove_at(HeapObj *self, Py_ssize_t i) {
    auto &e = *self->entries;
    Entry victim = e[i];
    Py_ssize_t last = (Py_ssize_t)e.size() - 1;
    if (i != last) heap_swap(self, i, last);
    // after the swap the victim sits at `last`
    e.pop_back();
    if (PyDict_DelItem(self->index, victim.key) < 0) {
        PyErr_Clear();  // index desync would be a bug; never leave errors
    }
    if (i < (Py_ssize_t)e.size()) heap_down(self, heap_up(self, i));
    PyObject *item = victim.item;  // transfer ownership to caller
    Py_DECREF(victim.key);
    return item;
}

static PyObject *heap_pop(HeapObj *self, PyObject *) {
    if (self->entries->empty()) Py_RETURN_NONE;
    return heap_remove_at(self, 0);
}

static PyObject *heap_peek(HeapObj *self, PyObject *) {
    if (self->entries->empty()) Py_RETURN_NONE;
    PyObject *item = (*self->entries)[0].item;
    Py_INCREF(item);
    return item;
}

static PyObject *heap_delete(HeapObj *self, PyObject *key) {
    PyObject *pos = PyDict_GetItemWithError(self->index, key);
    if (pos == nullptr) {
        if (PyErr_Occurred()) return nullptr;
        Py_RETURN_NONE;
    }
    Py_ssize_t i = PyLong_AsSsize_t(pos);
    if (i == -1 && PyErr_Occurred()) return nullptr;
    return heap_remove_at(self, i);
}

static PyObject *heap_get(HeapObj *self, PyObject *key) {
    PyObject *pos = PyDict_GetItemWithError(self->index, key);
    if (pos == nullptr) {
        if (PyErr_Occurred()) return nullptr;
        Py_RETURN_NONE;
    }
    Py_ssize_t i = PyLong_AsSsize_t(pos);
    if (i == -1 && PyErr_Occurred()) return nullptr;
    PyObject *item = (*self->entries)[i].item;
    Py_INCREF(item);
    return item;
}

static PyObject *heap_list(HeapObj *self, PyObject *) {
    Py_ssize_t n = (Py_ssize_t)self->entries->size();
    PyObject *out = PyList_New(n);
    if (out == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = (*self->entries)[i].item;
        Py_INCREF(item);
        PyList_SET_ITEM(out, i, item);
    }
    return out;
}

static Py_ssize_t heap_len(HeapObj *self) {
    return (Py_ssize_t)self->entries->size();
}

static int heap_contains(HeapObj *self, PyObject *key) {
    return PyDict_Contains(self->index, key);
}

static PyMethodDef heap_methods[] = {
    {"add", (PyCFunction)heap_add, METH_VARARGS,
     "add(key, a, b, item): insert or update-in-place by key"},
    {"pop", (PyCFunction)heap_pop, METH_NOARGS, "pop smallest item or None"},
    {"peek", (PyCFunction)heap_peek, METH_NOARGS, "smallest item or None"},
    {"delete", (PyCFunction)heap_delete, METH_O,
     "remove by key, returning the item or None"},
    {"get", (PyCFunction)heap_get, METH_O, "item by key or None"},
    {"list", (PyCFunction)heap_list, METH_NOARGS, "items, heap order"},
    {nullptr, nullptr, 0, nullptr},
};

static PySequenceMethods heap_as_sequence = {
    (lenfunc)heap_len,            // sq_length
    nullptr, nullptr, nullptr, nullptr, nullptr, nullptr,
    (objobjproc)heap_contains,    // sq_contains
    nullptr, nullptr,
};

static PyTypeObject HeapType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "kubernetes_tpu_native.KeyedHeap",       // tp_name
    sizeof(HeapObj),                         // tp_basicsize
};

// ------------------------------------------------------------- quantity

// Exact quantity parse -> __int128 with ceil rounding at a given scale.
// Returns 0 on success, -1 on malformed input, -2 on overflow (caller
// falls back to the arbitrary-precision Python path).
static inline bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
           c == '\f';
}

static int parse_quantity_scaled(const char *s, int extra_exp10,
                                 long long *out) {
    while (is_space(*s)) s++;
    bool neg = false;
    if (*s == '+') s++;
    else if (*s == '-') { neg = true; s++; }

    __int128 mant = 0;
    int frac_digits = 0;
    bool any_digit = false, in_frac = false;
    for (; *s; s++) {
        if (*s >= '0' && *s <= '9') {
            mant = mant * 10 + (*s - '0');
            if (mant > (__int128)1 << 100) return -2;
            if (in_frac) frac_digits++;
            any_digit = true;
        } else if (*s == '.') {
            if (in_frac) return -1;
            in_frac = true;
        } else {
            break;
        }
    }
    if (!any_digit) return -1;

    long exp10 = 0;
    if (*s == 'e' || *s == 'E') {
        // only an exponent when digits follow — otherwise this is the E
        // (exa) or Ei (exbi) SUFFIX ("1E", "2Ei")
        const char *save = s;
        s++;
        bool eneg = false;
        if (*s == '+') s++;
        else if (*s == '-') { eneg = true; s++; }
        if (*s >= '0' && *s <= '9') {
            for (; *s >= '0' && *s <= '9'; s++) {
                exp10 = exp10 * 10 + (*s - '0');
                if (exp10 > 40) return -2;
            }
            if (eneg) exp10 = -exp10;
        } else {
            s = save;
        }
    }

    long long bin_mult = 1;
    if (*s != '\0' && !is_space(*s)) {
        if (s[1] == 'i') {
            switch (s[0]) {
                case 'K': bin_mult = 1LL << 10; break;
                case 'M': bin_mult = 1LL << 20; break;
                case 'G': bin_mult = 1LL << 30; break;
                case 'T': bin_mult = 1LL << 40; break;
                case 'P': bin_mult = 1LL << 50; break;
                case 'E': bin_mult = 1LL << 60; break;
                default: return -1;
            }
            s += 2;
        } else {
            switch (s[0]) {
                case 'n': exp10 -= 9; break;
                case 'u': exp10 -= 6; break;
                case 'm': exp10 -= 3; break;
                case 'k': exp10 += 3; break;
                case 'M': exp10 += 6; break;
                case 'G': exp10 += 9; break;
                case 'T': exp10 += 12; break;
                case 'P': exp10 += 15; break;
                case 'E': exp10 += 18; break;
                default: return -1;
            }
            s += 1;
        }
    }
    while (is_space(*s)) s++;
    if (*s != '\0') return -1;

    exp10 += extra_exp10 - frac_digits;
    // overflow discipline: every multiply is guarded BEFORE it happens
    // (signed __int128 overflow is UB, and a wrapped value would silently
    // under-reserve); -2 sends the caller to the arbitrary-precision path
    const __int128 LIMIT = (__int128)1 << 126;
    if (bin_mult > 1 && mant > LIMIT / bin_mult) return -2;
    __int128 v = mant * (__int128)bin_mult;
    while (exp10 > 0) {
        if (v > LIMIT / 10) return -2;
        v *= 10;
        exp10--;
    }
    bool inexact = false;
    while (exp10 < 0) {
        inexact = inexact || (v % 10 != 0);
        v /= 10;
        exp10++;
    }
    if (neg) {
        // requests are never negative in practice; mirror Decimal math:
        // ceil(-x) drops the fraction toward zero
        v = -v;
    } else if (inexact) {
        v += 1;  // ceil
    }
    if (v > (__int128)INT64_MAX || v < (__int128)INT64_MIN) return -2;
    *out = (long long)v;
    return 0;
}

static PyObject *quantity_call(PyObject *arg, int extra_exp10) {
    const char *s = PyUnicode_AsUTF8(arg);
    if (s == nullptr) return nullptr;
    long long out;
    int rc = parse_quantity_scaled(s, extra_exp10, &out);
    if (rc == -1) {
        PyErr_Format(PyExc_ValueError, "malformed quantity %R", arg);
        return nullptr;
    }
    if (rc == -2) {
        PyErr_Format(PyExc_OverflowError, "quantity out of range: %R", arg);
        return nullptr;
    }
    return PyLong_FromLongLong(out);
}

static PyObject *parse_milli(PyObject *, PyObject *arg) {
    return quantity_call(arg, 3);   // Quantity.MilliValue, ceil
}

static PyObject *parse_ceil(PyObject *, PyObject *arg) {
    return quantity_call(arg, 0);   // Quantity.Value, ceil
}

// ------------------------------------------------------------- module

static PyMethodDef module_methods[] = {
    {"parse_milli", parse_milli, METH_O,
     "quantity string -> integer units*1000, ceil (MilliValue)"},
    {"parse_ceil", parse_ceil, METH_O,
     "quantity string -> integer units, ceil (Value)"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef native_module = {
    PyModuleDef_HEAD_INIT,
    "kubernetes_tpu_native",
    "C++ host structures for the TPU scheduler (heap, quantity parse)",
    -1,
    module_methods,
};

}  // namespace

PyMODINIT_FUNC PyInit_kubernetes_tpu_native(void) {
    HeapType.tp_dealloc = (destructor)heap_dealloc;
    HeapType.tp_flags = Py_TPFLAGS_DEFAULT;
    HeapType.tp_doc = "map-indexed binary heap ordered by (a, b) doubles";
    HeapType.tp_methods = heap_methods;
    HeapType.tp_new = heap_new;
    HeapType.tp_as_sequence = &heap_as_sequence;
    if (PyType_Ready(&HeapType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&native_module);
    if (m == nullptr) return nullptr;
    Py_INCREF(&HeapType);
    if (PyModule_AddObject(m, "KeyedHeap", (PyObject *)&HeapType) < 0) {
        Py_DECREF(&HeapType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
