"""Loader for the C++ host extension (src/_native.cpp).

Compiles the module once per environment on first import (g++ into
``_build/``, atomic rename) and exposes it as ``mod``; ``mod is None``
when no toolchain is available or the build fails, and every consumer
(backend.heap, utils.quantity) silently uses its pure-Python path. Set
``KUBERNETES_TPU_NO_NATIVE=1`` to force the fallback.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "_native.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD_DIR, "kubernetes_tpu_native.so")


def _build() -> bool:
    tmp = None
    try:
        include = sysconfig.get_paths()["include"]
        os.makedirs(_BUILD_DIR, exist_ok=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
        os.close(fd)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               f"-I{include}", _SRC, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)  # atomic: concurrent builders race safely
        return True
    except (OSError, subprocess.SubprocessError):
        # no toolchain, read-only install dir, sandbox… — ANY failure here
        # must mean "Python engines", never an import-time crash
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def _load():
    if os.environ.get("KUBERNETES_TPU_NO_NATIVE"):
        return None
    if not os.path.exists(_SO):
        # stale check is deliberate and cheap: rebuild when the source is
        # newer than the artifact (dev edits)
        if not _build():
            return None
    elif os.path.getmtime(_SRC) > os.path.getmtime(_SO):
        if not _build():
            return None
    try:
        spec = importlib.util.spec_from_file_location(
            "kubernetes_tpu_native", _SO)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        return m
    except Exception:  # noqa: BLE001 — any load failure means fallback
        return None


mod = _load()
