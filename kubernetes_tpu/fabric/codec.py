"""Binary wire codec: msgpack-style tagged values + struct encoding.

The compact alternative to :mod:`kubernetes_tpu.utils.wire` + JSON on
the hubserver/hubclient hot path. Two ideas carry the size win:

* **msgpack-style value tags** — small ints, short strings, and small
  containers encode in one tag byte plus payload; ``None``/booleans are
  a single byte (JSON spells ``null`` per *field name* per object).
* **positional structs** — a dataclass encodes as a struct tag, a
  16-bit kind id, and its field VALUES in dataclass field order. Field
  names never go on the wire; both ends recover them from the shared
  class registry (the same one utils.wire uses). That is safe only when
  both ends agree on every kind's field list, which is exactly what the
  **registry fingerprint** pins: a hash over every kind name and its
  ordered field names. Negotiation (hubserver/hubclient) exchanges the
  fingerprint and falls back to JSON on any mismatch, so a version-
  skewed peer degrades to the self-describing wire instead of
  mis-zipping fields.

Framing for streams (the /watch wire): one event per frame, a 4-byte
big-endian length prefix then the payload — binary-safe (payloads may
contain newlines), unlike the JSON-lines wire.

The codec is self-contained on purpose: no third-party msgpack, no
compression (the win here is structural, and stays cheap to reason
about), and the JSON wire remains fully supported — old clients, the
WAL, and JSON-era middleboxes (the chaos proxy) keep working.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import fields as dc_fields
from dataclasses import is_dataclass
from typing import Any

CODEC_BINARY = "bin1"            # wire name of this codec version
CODEC_JSON = "json"              # the fallback (utils.wire + JSON)
WIRE_HEADER = "X-KTPU-Codec"     # negotiation header (see hubserver)

# value tags (msgpack-compatible ranges where it is convenient; the two
# codecs never interoperate byte-for-byte, the familiarity is for
# readers)
_NIL = 0xC0
_FALSE = 0xC2
_TRUE = 0xC3
_BIN8, _BIN16, _BIN32 = 0xC4, 0xC5, 0xC6
_FLOAT64 = 0xCB
_UINT8, _UINT16, _UINT32, _UINT64 = 0xCC, 0xCD, 0xCE, 0xCF
_INT8, _INT16, _INT32, _INT64 = 0xD0, 0xD1, 0xD2, 0xD3
_STRUCT = 0xD4                   # + uint16 kind id + fields positionally
_SET = 0xD5                      # + array of members
_STR8, _STR16, _STR32 = 0xD9, 0xDA, 0xDB
_ARR16, _ARR32 = 0xDC, 0xDD
_MAP16, _MAP32 = 0xDE, 0xDF
_FIXMAP = 0x80                   # 0x80-0x8f: map, len in low nibble
_FIXARR = 0x90                   # 0x90-0x9f
_FIXSTR = 0xA0                   # 0xa0-0xbf: str, len in low 5 bits
_NEGFIX = 0xE0                   # 0xe0-0xff: -32..-1


_KINDS: list[tuple[str, type, tuple[str, ...]]] = []   # sorted by name
_KIND_ID: dict[type, int] = {}
_FINGERPRINT: str | None = None


def _build_registry() -> None:
    """Freeze the struct table from utils.wire's class registry: kind
    ids are indices into the name-sorted kind list, field order is the
    dataclass declaration order. Both ends derive the same table from
    the same code; the fingerprint proves it before any positional
    decode happens."""
    global _FINGERPRINT
    if _KINDS:
        return
    from kubernetes_tpu.utils.wire import _registry

    for name in sorted(_registry()):
        cls = _registry()[name]
        fnames = tuple(f.name for f in dc_fields(cls))
        _KIND_ID[cls] = len(_KINDS)
        _KINDS.append((name, cls, fnames))
    h = hashlib.sha256()
    for name, _, fnames in _KINDS:
        h.update(name.encode())
        h.update(b"(" + ",".join(fnames).encode() + b");")
    _FINGERPRINT = h.hexdigest()[:16]


def registry_fingerprint() -> str:
    """Hash of every wire kind's name + ordered field names. Equal
    fingerprints make positional struct decode safe; negotiation falls
    back to JSON on mismatch."""
    _build_registry()
    return _FINGERPRINT  # type: ignore[return-value]


def offer() -> str:
    """The client's negotiation header value: "I speak bin1 with this
    registry shape". Servers confirm (see hubserver) only on an exact
    fingerprint match."""
    return f"{CODEC_BINARY};fp={registry_fingerprint()}"


# (the server-side parse of the offer — body codec + fingerprint match
# — lives in hubserver._parse_codec_header, the one consumer)


# ------------------------------ encode ------------------------------


def _enc_int(out: bytearray, v: int) -> None:
    if 0 <= v <= 0x7F:
        out.append(v)
    elif -32 <= v < 0:
        out.append(0x100 + v)
    elif 0 <= v <= 0xFF:
        out.append(_UINT8)
        out.append(v)
    elif 0 <= v <= 0xFFFF:
        out.append(_UINT16)
        out += v.to_bytes(2, "big")
    elif 0 <= v <= 0xFFFFFFFF:
        out.append(_UINT32)
        out += v.to_bytes(4, "big")
    elif 0 <= v <= 0xFFFFFFFFFFFFFFFF:
        out.append(_UINT64)
        out += v.to_bytes(8, "big")
    elif -0x80 <= v < 0:
        out.append(_INT8)
        out += v.to_bytes(1, "big", signed=True)
    elif -0x8000 <= v < 0:
        out.append(_INT16)
        out += v.to_bytes(2, "big", signed=True)
    elif -0x80000000 <= v < 0:
        out.append(_INT32)
        out += v.to_bytes(4, "big", signed=True)
    elif -0x8000000000000000 <= v < 0:
        out.append(_INT64)
        out += v.to_bytes(8, "big", signed=True)
    else:
        raise OverflowError(f"int {v} exceeds 64 bits")


def _enc_len(out: bytearray, n: int, fix_tag: int, fix_max: int,
             tags: tuple[int, ...]) -> None:
    """Length header for str/array/map: fix form when it fits, else the
    8/16/32-bit escape tags."""
    if n <= fix_max:
        out.append(fix_tag | n)
    elif len(tags) == 3 and n <= 0xFF:
        out.append(tags[0])
        out.append(n)
    elif n <= 0xFFFF:
        out.append(tags[-2])
        out += n.to_bytes(2, "big")
    elif n <= 0xFFFFFFFF:
        out.append(tags[-1])
        out += n.to_bytes(4, "big")
    else:
        raise OverflowError(f"container of {n} items exceeds 32 bits")


def _encode(out: bytearray, v: Any) -> None:
    if v is None:
        out.append(_NIL)
    elif v is True:
        out.append(_TRUE)
    elif v is False:
        out.append(_FALSE)
    elif type(v) is int:
        _enc_int(out, v)
    elif type(v) is float:
        out.append(_FLOAT64)
        out += struct.pack(">d", v)
    elif type(v) is str:
        b = v.encode("utf-8")
        _enc_len(out, len(b), _FIXSTR, 31, (_STR8, _STR16, _STR32))
        out += b
    elif is_dataclass(v) and not isinstance(v, type):
        kid = _KIND_ID.get(type(v))
        if kid is None:
            raise ValueError(f"unknown wire kind {type(v).__name__!r}")
        out.append(_STRUCT)
        out += kid.to_bytes(2, "big")
        for f in _KINDS[kid][2]:
            _encode(out, getattr(v, f))
    elif isinstance(v, dict):
        _enc_len(out, len(v), _FIXMAP, 15, (_MAP16, _MAP32))
        for k, x in v.items():
            _encode(out, k)
            _encode(out, x)
    elif isinstance(v, (list, tuple)):
        _enc_len(out, len(v), _FIXARR, 15, (_ARR16, _ARR32))
        for x in v:
            _encode(out, x)
    elif isinstance(v, (set, frozenset)):
        items = list(v)
        try:
            items.sort()               # wire stability, like utils.wire
        except TypeError:
            items.sort(key=repr)
        out.append(_SET)
        _enc_len(out, len(items), _FIXARR, 15, (_ARR16, _ARR32))
        for x in items:
            _encode(out, x)
    elif isinstance(v, (bytes, bytearray)):
        n = len(v)
        if n <= 0xFF:
            out.append(_BIN8)
            out.append(n)
        elif n <= 0xFFFF:
            out.append(_BIN16)
            out += n.to_bytes(2, "big")
        else:
            out.append(_BIN32)
            out += n.to_bytes(4, "big")
        out += v
    elif isinstance(v, bool):          # numpy-ish bool subclasses
        out.append(_TRUE if v else _FALSE)
    elif isinstance(v, int):           # int subclasses (enums)
        _enc_int(out, int(v))
    elif isinstance(v, float):
        out.append(_FLOAT64)
        out += struct.pack(">d", float(v))
    else:
        raise TypeError(f"cannot encode {type(v).__name__}")


def encode(v: Any) -> bytes:
    """Value -> bin1 bytes. Dataclasses from the wire registry encode
    positionally; everything JSON-able (plus sets/bytes) round-trips."""
    _build_registry()
    out = bytearray()
    _encode(out, v)
    return bytes(out)


# ------------------------------ decode ------------------------------


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) < n:
            raise ValueError("truncated bin1 payload")
        self.pos += n
        return b

    def u(self, n: int) -> int:
        return int.from_bytes(self.take(n), "big")


def _decode(r: _Reader) -> Any:
    tag = r.u(1)
    if tag <= 0x7F:
        return tag
    if tag >= _NEGFIX:
        return tag - 0x100
    if _FIXSTR <= tag <= 0xBF:
        return r.take(tag & 0x1F).decode("utf-8")
    if _FIXMAP <= tag <= 0x8F:
        return {_decode(r): _decode(r) for _ in range(tag & 0x0F)}
    if _FIXARR <= tag <= 0x9F:
        return [_decode(r) for _ in range(tag & 0x0F)]
    if tag == _NIL:
        return None
    if tag == _TRUE:
        return True
    if tag == _FALSE:
        return False
    if tag == _FLOAT64:
        return struct.unpack(">d", r.take(8))[0]
    if tag == _UINT8:
        return r.u(1)
    if tag == _UINT16:
        return r.u(2)
    if tag == _UINT32:
        return r.u(4)
    if tag == _UINT64:
        return r.u(8)
    if tag == _INT8:
        return int.from_bytes(r.take(1), "big", signed=True)
    if tag == _INT16:
        return int.from_bytes(r.take(2), "big", signed=True)
    if tag == _INT32:
        return int.from_bytes(r.take(4), "big", signed=True)
    if tag == _INT64:
        return int.from_bytes(r.take(8), "big", signed=True)
    if tag == _STR8:
        return r.take(r.u(1)).decode("utf-8")
    if tag == _STR16:
        return r.take(r.u(2)).decode("utf-8")
    if tag == _STR32:
        return r.take(r.u(4)).decode("utf-8")
    if tag == _ARR16:
        return [_decode(r) for _ in range(r.u(2))]
    if tag == _ARR32:
        return [_decode(r) for _ in range(r.u(4))]
    if tag == _MAP16:
        return {_decode(r): _decode(r) for _ in range(r.u(2))}
    if tag == _MAP32:
        return {_decode(r): _decode(r) for _ in range(r.u(4))}
    if tag == _BIN8:
        return r.take(r.u(1))
    if tag == _BIN16:
        return r.take(r.u(2))
    if tag == _BIN32:
        return r.take(r.u(4))
    if tag == _SET:
        arr = _decode(r)
        return set(arr)
    if tag == _STRUCT:
        kid = r.u(2)
        if kid >= len(_KINDS):
            raise ValueError(f"unknown bin1 kind id {kid}")
        _, cls, fnames = _KINDS[kid]
        return cls(**{f: _decode(r) for f in fnames})
    raise ValueError(f"bad bin1 tag 0x{tag:02x}")


def decode(data: bytes) -> Any:
    """bin1 bytes -> value. The inverse of :func:`encode`; only safe
    against payloads from a fingerprint-matched peer (negotiation
    guarantees that before this is ever called on the wire)."""
    _build_registry()
    r = _Reader(data)
    v = _decode(r)
    if r.pos != len(data):
        raise ValueError(f"{len(data) - r.pos} trailing bytes "
                         "after bin1 value")
    return v


# ------------------------------ framing ------------------------------


def frame(payload: bytes) -> bytes:
    """Length-prefix one stream frame (4-byte big-endian length)."""
    return len(payload).to_bytes(4, "big") + payload


def read_frame(fp) -> bytes | None:
    """Read one frame off a stream supporting ``read(n)``; None on a
    clean or torn EOF (a cut stream ends mid-frame — callers treat both
    as the connection dying, exactly like a cut JSON line)."""
    hdr = _read_exact(fp, 4)
    if hdr is None:
        return None
    return _read_exact(fp, int.from_bytes(hdr, "big"))


def _read_exact(fp, n: int) -> bytes | None:
    if n == 0:
        return b""
    chunks = []
    got = 0
    while got < n:
        b = fp.read(n - got)
        if not b:
            return None
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)
