"""Replicated state core: a 3-process quorum for rv / fencing / ring.

PR 11 left one rung on the fabric's failure ladder labelled "restart
the universe": the StateCore — rv allocation, lease fencing, the crc32
ring map — was the one stop-the-world process, run like etcd but
without etcd's Raft. This module closes it with a **Raft-lite**
replication protocol over the existing bin1 ``/call`` wire:

* **leader election** — replicas heartbeat; a follower that stops
  hearing from the leader campaigns with a term bump and a log
  up-to-date check, exactly Raft's vote rule, so the new leader always
  holds every committed entry;
* **log replication with majority-ack before release** — every
  mutating verb (``rv.next``, ``leases.update``, ``fabric_set_ring``,
  ``rv.advance_to``) is a term-stamped log entry. The leader answers
  the caller only after a majority has durably appended the entry, so
  a deposed leader can never have handed out an rv or fencing epoch
  that the surviving quorum doesn't know about: across a ``kill -9``
  mid-``rv.next``, the value was either committed (and the new leader
  re-derives it by applying the same log) or never released (and the
  caller's retry draws a fresh one — a harmless gap, never a reuse);
* **per-replica bin1 WALs** — term/vote changes and log entries are
  length-prefixed binary frames (torn-tail tolerant, like the journal
  WAL); a ``kill -9``'d replica replays its WAL into log-consistent
  state and rejoins as a follower, catching up from the leader;
* **leader-lease reads** — the leader serves reads only while it has
  majority contact inside the lease window (shorter than the minimum
  election timeout), so a partitioned ex-leader parks instead of
  serving stale fencing epochs. Followers serve the *non-fencing*
  reads (ring, topology, registries, ``rv.last``) with the same
  staleness bound; fencing reads (``leases.epoch_of``) are
  leader-only — a lagging follower answering "epoch 3" after the
  quorum committed 4 would un-fence a deposed scheduler;
* **NotLeader redirects** — a verb landing on a non-leader answers a
  typed ``NotLeader`` carrying the leader URL and term; callers
  (:class:`ReplicaClient`) re-resolve and retry instead of erroring,
  riding out elections under a deadline.

Registries (shards / routers / relays) stay **soft state**: they are
heartbeat-refreshed every couple of seconds by their owners, so they
are gossiped from leader to followers on every heartbeat instead of
being logged — a new leader starts from its gossip mirror and is
re-confirmed by the next registration wave.

:class:`ReplicaClient` is the client half: a RemoteHub-shaped facade
over the replica set (``.rv`` / ``.leases`` namespaces plus the
``fabric_*`` verbs) that discovers the full replica set from any
member, caches the leader, follows redirect hints, and rotates
through candidates during elections. ``ProcShardHub``,
``ClusterClient``, and the router all speak it transparently — a
comma-separated state URL is the only deployment-visible change.
"""

from __future__ import annotations

import os
import random
import threading
import time

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.fabric.cluster import RING_SLOTS, RELAY_TTL_S
from kubernetes_tpu.hub import NotFound, NotLeader, Unavailable
from kubernetes_tpu.leaderelection import SCHEDULER_TTL_S, LeaseStore

ROLE_LEADER = "leader"
ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"


# --------------------------------------------------------------------------
# the per-replica WAL: hard state + log entries as bin1 frames
# --------------------------------------------------------------------------


class ReplicaWal:
    """Append-only bin1 record stream for one replica's durable state:

    * ``{"hs": {"t": term, "v": voted_for}}`` — hard-state change
      (term bump / vote), persisted BEFORE the RPC answer that makes
      the promise (Raft's persistence rule);
    * ``{"e": {"i": index, "t": term, "op": [...]}}`` — one log entry
      (``i`` is the ABSOLUTE log index);
    * ``{"tr": index}`` — truncate: entries above ``index`` were
      overwritten by a newer leader's log;
    * ``{"snap": {"idx", "term", "state"}}`` — a log-compaction
      snapshot: the state machine at ``idx``; entries at or below it
      are gone from the file (the compaction ``rewrite`` emits this
      first, then the surviving suffix).

    Replay rebuilds (term, voted_for, snapshot, log-suffix). The
    commit index is NOT persisted (standard Raft): a restarted replica
    re-learns it from the leader and re-applies from the snapshot —
    apply is deterministic, so the rebuilt state machine is
    bit-identical. A torn final frame (the ``kill -9`` landed
    mid-write) never committed anywhere and is dropped, exactly the
    journal WAL's tolerance."""

    def __init__(self, path: str | None):
        self.path = path
        self._fh = open(path, "ab") if path else None

    def replay(self) -> tuple[int, str | None, dict | None,
                              list[tuple[int, list]]]:
        """-> (term, voted_for, snapshot|None, log suffix) from disk.
        The log list holds entries snapshot.idx+1.. (or 1.. when no
        snapshot record exists)."""
        term, voted = 0, None
        snap: dict | None = None
        floor = 0
        log: list[tuple[int, list]] = []
        if not self.path or not os.path.exists(self.path):
            return term, voted, snap, log
        with open(self.path, "rb") as f:
            size = os.path.getsize(self.path)
            pos = 0
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    break                 # clean EOF / torn length
                n = int.from_bytes(hdr, "big")
                payload = f.read(n)
                if len(payload) < n:
                    break                 # torn frame: never committed
                end = pos + 4 + n
                try:
                    rec = binwire.decode(payload)
                except ValueError:
                    if end >= size:
                        break             # torn final frame
                    raise                 # interior corruption: loud
                pos = end
                if "hs" in rec:
                    term = int(rec["hs"]["t"])
                    voted = rec["hs"]["v"]
                elif "snap" in rec:
                    snap = dict(rec["snap"])
                    floor = int(snap["idx"])
                    log = []
                elif "tr" in rec:
                    del log[max(0, int(rec["tr"]) - floor):]
                elif "e" in rec:
                    e = rec["e"]
                    i = int(e["i"])
                    if i <= floor:
                        continue          # already inside the snapshot
                    # an entry record names its ABSOLUTE index: replay
                    # after a truncate-then-append lands in place
                    del log[i - 1 - floor:]
                    log.append((int(e["t"]), list(e["op"])))
        return term, voted, snap, log

    def _write(self, rec: dict) -> None:
        if self._fh is not None:
            self._fh.write(binwire.frame(binwire.encode(rec)))
            self._fh.flush()

    def hard_state(self, term: int, voted: str | None) -> None:
        self._write({"hs": {"t": term, "v": voted}})

    def entry(self, index: int, term: int, op: list) -> None:
        self._write({"e": {"i": index, "t": term, "op": op}})

    def truncate(self, keep: int) -> None:
        self._write({"tr": keep})

    def rewrite(self, term: int, voted: str | None, snap: dict,
                entries: list[tuple[int, int, list]]) -> None:
        """Atomically replace the file with hard state + a snapshot +
        the surviving log suffix (``entries`` = (index, term, op)):
        the compaction that keeps the WAL from growing with every rv
        the fleet ever drew."""
        if not self.path:
            return
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            f.write(binwire.frame(binwire.encode(
                {"hs": {"t": term, "v": voted}})))
            f.write(binwire.frame(binwire.encode({"snap": snap})))
            for i, t, op in entries:
                f.write(binwire.frame(binwire.encode(
                    {"e": {"i": i, "t": t, "op": op}})))
            f.flush()
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "ab")

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None


# --------------------------------------------------------------------------
# the replica
# --------------------------------------------------------------------------


class StateReplica:
    """One member of the replicated state core. Serve it with the
    ordinary ``HubServer`` — the Raft RPCs, the public state verbs,
    codec negotiation, and typed errors all ride the stock /call wire.

    The applied state machine is exactly StateCore's state: the rv
    counter, the LeaseStore (fencing epochs), and the ring map — all
    rebuilt deterministically by applying the committed log in order,
    which is what makes "no rv reused, epochs monotone" a property of
    the log rather than of any one process's memory."""

    def __init__(self, name: str, peers: dict[str, str] | None = None,
                 pod_shards: list[str] | None = None,
                 ring_slots: int = RING_SLOTS,
                 wal_path: str | None = None,
                 heartbeat_s: float = 0.15,
                 election_timeout_s: tuple[float, float] = (0.6, 1.2),
                 rpc_timeout: float = 1.5,
                 client_factory=None, seed: int | None = None,
                 log_compact_threshold: int = 4096):
        self.name = name
        self.shard_name = name               # /metrics identity label
        self._peers: dict[str, str] = dict(peers or {name: ""})
        self._heartbeat_s = heartbeat_s
        self._eto = election_timeout_s
        # leader lease: shorter than the minimum election timeout, so a
        # deposed leader's lease expires before a successor can win
        self._lease_s = election_timeout_s[0] * 0.9
        self._rpc_timeout = rpc_timeout
        self._compact_threshold = log_compact_threshold
        self._rng = random.Random(seed if seed is not None
                                  else hash(name) & 0xFFFF)
        self._lock = threading.RLock()
        self._repl_lock = threading.Lock()   # serializes AE rounds
        self._wal = ReplicaWal(wal_path)
        self._term, self._voted_for, snap, self._log = \
            self._wal.replay()
        self._role = ROLE_FOLLOWER
        self._leader: str | None = None
        # log compaction floor: the log list holds entries
        # (floor_idx, floor_idx + len]; everything at or below the
        # floor is summarized by the applied snapshot
        self._floor_idx = 0
        self._floor_term = 0
        self._commit = 0
        self._applied = 0
        self._results: dict[int, tuple[int, object]] = {}
        # per-peer replication state (leader-only)
        self._next_idx: dict[str, int] = {}
        self._match_idx: dict[str, int] = {}
        self._last_ack: dict[str, float] = {}
        self._last_heard = time.monotonic()
        self._last_sent = 0.0
        self._timeout = self._rng.uniform(*self._eto)
        # ---- the state machine (StateCore's state, log-applied) ----
        self._sm_rv = 0
        self._sm_leases = LeaseStore()
        names = list(pod_shards or [])
        self._sm_ring = {"epoch": 1,
                         "slots": [names[i % len(names)]
                                   for i in range(ring_slots)]} \
            if names else {"epoch": 0, "slots": []}
        # scheduler slice ring: logged (not soft) — the slice map must
        # survive a state-leader failover or every scheduler replica
        # would race a from-scratch rebalance against epoch 0
        self._sm_sched_ring = {"epoch": 0, "slots": []}
        # ---- soft state (gossiped, never logged) ----
        self._shards: dict[str, dict] = {}
        self._routers: dict[str, dict] = {}
        self._relays: dict[str, dict] = {}
        self._schedulers: dict[str, dict] = {}
        self._clients: dict[str, object] = {}
        if client_factory is None:
            from kubernetes_tpu.hubclient import RemoteHub

            client_factory = lambda url: RemoteHub(  # noqa: E731
                url, timeout=self._rpc_timeout,
                retry_deadline=0.0)      # Raft RPCs never blind-retry
        self._factory = client_factory
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None
        # a replayed snapshot re-seeds the state machine at its floor;
        # the log suffix above it re-applies once the leader tells us
        # the commit index (or we become leader and commit a barrier)
        if snap is not None:
            self._install_snapshot_locked(snap, persist=False)
        # dotted-verb surfaces (the /call wire's rv.* / leases.*)
        self.rv = _ReplicaRv(self)
        self.leases = _ReplicaLeases(self)

    # ------------- log indexing (compaction-floor aware) -------------

    def _last_index(self) -> int:
        return self._floor_idx + len(self._log)

    def _term_at(self, idx: int) -> int:
        """Term of the entry at absolute index ``idx`` (the floor's
        recorded term at the floor itself; 0 when unknown)."""
        if idx == self._floor_idx:
            return self._floor_term
        if idx < self._floor_idx or idx > self._last_index():
            return 0
        return self._log[idx - self._floor_idx - 1][0]

    # ------------- lifecycle -------------

    def set_peers(self, peers: dict[str, str]) -> None:
        """Pin the replica-set map (name -> URL) before ``start()`` —
        in-thread tests learn ports only after binding servers."""
        with self._lock:
            self._peers = dict(peers)

    def start(self) -> "StateReplica":
        self._ticker = threading.Thread(target=self._tick_loop,
                                        daemon=True,
                                        name=f"state-replica-{self.name}")
        self._ticker.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2)
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._wal.close()

    def _client(self, peer: str):
        with self._lock:
            c = self._clients.get(peer)
            if c is None:
                url = self._peers.get(peer)
                if not url:
                    raise NotFound(f"unknown replica {peer!r}")
                c = self._clients[peer] = self._factory(url)
            return c

    def _other_peers(self) -> list[str]:
        return [p for p in self._peers if p != self.name]

    def _majority(self) -> int:
        return len(self._peers) // 2 + 1

    # ------------- ticker: elections + heartbeats -------------

    def _tick_loop(self) -> None:
        while not self._stop.wait(0.03):
            try:
                with self._lock:
                    role = self._role
                    now = time.monotonic()
                    due = now - self._last_sent >= self._heartbeat_s
                    timed_out = (role != ROLE_LEADER
                                 and now - self._last_heard
                                 >= self._timeout)
                if role == ROLE_LEADER:
                    if due:
                        self._replication_round()
                elif timed_out:
                    self._campaign()
            except Exception:  # noqa: BLE001 — the ticker must survive
                pass           # any transient RPC/teardown race

    def _campaign(self) -> None:
        with self._lock:
            if len(self._peers) == 1:
                # degenerate single-replica cluster: instant leadership
                self._term += 1
                self._voted_for = self.name
                self._wal.hard_state(self._term, self._voted_for)
                self._become_leader_locked()
                return
            self._term += 1
            self._voted_for = self.name
            self._wal.hard_state(self._term, self._voted_for)
            self._role = ROLE_CANDIDATE
            self._leader = None
            self._last_heard = time.monotonic()
            self._timeout = self._rng.uniform(*self._eto)
            term = self._term
            last_idx = self._last_index()
            last_term = self._term_at(last_idx)
        votes = [1]          # self
        done = threading.Event()
        peers = self._other_peers()

        def ask(peer: str) -> None:
            try:
                r = self._client(peer).replica_request_vote(
                    term, self.name, last_idx, last_term)
            except Exception:  # noqa: BLE001 — peer down/unreachable
                return
            with self._lock:
                if r.get("term", 0) > self._term:
                    self._become_follower_locked(r["term"])
                    done.set()
                    return
                if r.get("granted") and self._role == ROLE_CANDIDATE \
                        and self._term == term:
                    votes[0] += 1
                    if votes[0] >= self._majority():
                        self._become_leader_locked()
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True)
                   for p in peers]
        for t in threads:
            t.start()
        done.wait(self._rpc_timeout)
        if self._is_leader():
            # commit a barrier no-op in the new term: Raft's rule that
            # a leader only commits entries of its OWN term — the
            # barrier drags every prior committed entry with it
            try:
                self._propose(["noop"])
            except (NotLeader, Unavailable):
                pass

    def _is_leader(self) -> bool:
        with self._lock:
            return self._role == ROLE_LEADER

    def _become_leader_locked(self) -> None:
        self._role = ROLE_LEADER
        self._leader = self.name
        now = time.monotonic()
        self._last_sent = 0.0
        for p in self._other_peers():
            self._next_idx[p] = self._last_index() + 1
            self._match_idx[p] = 0
            self._last_ack[p] = now   # grace: the vote WAS the contact

    def _become_follower_locked(self, term: int,
                                leader: str | None = None) -> None:
        if term > self._term:
            self._term = term
            self._voted_for = None
            self._wal.hard_state(self._term, self._voted_for)
        self._role = ROLE_FOLLOWER
        if leader is not None:
            self._leader = leader
        self._last_heard = time.monotonic()
        self._timeout = self._rng.uniform(*self._eto)

    # ------------- replication (leader side) -------------

    def _replication_round(self) -> None:
        """One append-entries round to every peer (heartbeat when there
        is nothing to send), advancing the commit index on majority
        match. Serialized: concurrent proposers share rounds instead of
        interleaving per-peer cursors."""
        with self._repl_lock:
            with self._lock:
                if self._role != ROLE_LEADER:
                    return
                term = self._term
                commit = self._commit
                soft = {"shards": {n: dict(s)
                                   for n, s in self._shards.items()},
                        "routers": {n: dict(r)
                                    for n, r in self._routers.items()},
                        "relays": {n: dict(r)
                                   for n, r in self._relays.items()},
                        "schedulers": {n: dict(r) for n, r in
                                       self._schedulers.items()}}
                batches = {}
                for p in self._other_peers():
                    ni = self._next_idx.get(p, self._last_index() + 1)
                    snapshot = None
                    if ni <= self._floor_idx:
                        # the peer is behind the compaction floor:
                        # entries below it no longer exist — install
                        # the (tiny) state-machine snapshot and ship
                        # the suffix above the applied index
                        snapshot = {"idx": self._applied,
                                    "term": self._term_at(self._applied),
                                    "state": self._sm_dump_locked()}
                        prev_idx = self._applied
                    else:
                        prev_idx = ni - 1
                    prev_term = self._term_at(prev_idx)
                    entries = [{"i": prev_idx + 1 + j, "t": t, "op": op}
                               for j, (t, op) in enumerate(
                                   self._log[prev_idx
                                             - self._floor_idx:])]
                    batches[p] = (prev_idx, prev_term, entries,
                                  snapshot)
                self._last_sent = time.monotonic()
            replies: dict[str, dict | None] = {}

            def send(peer: str) -> None:
                prev_idx, prev_term, entries, snapshot = batches[peer]
                try:
                    replies[peer] = self._client(peer) \
                        .replica_append_entries(
                            term, self.name, prev_idx, prev_term,
                            entries, commit, soft, snapshot)
                except Exception:  # noqa: BLE001 — peer down: no ack
                    replies[peer] = None

            threads = [threading.Thread(target=send, args=(p,),
                                        daemon=True) for p in batches]
            for t in threads:
                t.start()
            for t in threads:
                t.join(self._rpc_timeout)
            with self._lock:
                if self._role != ROLE_LEADER or self._term != term:
                    return
                now = time.monotonic()
                for p, r in replies.items():
                    if r is None:
                        continue
                    if r.get("term", 0) > self._term:
                        self._become_follower_locked(r["term"])
                        return
                    self._last_ack[p] = now
                    if r.get("ok"):
                        m = int(r.get("match", 0))
                        self._match_idx[p] = max(self._match_idx[p], m)
                        self._next_idx[p] = self._match_idx[p] + 1
                    else:
                        # log mismatch: walk next_idx back (the reply
                        # hints how far the follower's log reaches)
                        hint = int(r.get("match",
                                         self._next_idx[p] - 2))
                        self._next_idx[p] = max(1, min(
                            self._next_idx[p] - 1, hint + 1))
                # majority-match commit, own-term entries only
                matches = sorted([self._last_index()]
                                 + list(self._match_idx.values()),
                                 reverse=True)
                candidate = matches[self._majority() - 1]
                if candidate > self._commit and candidate >= 1 \
                        and self._term_at(candidate) == self._term:
                    self._commit = candidate
                    self._apply_locked()

    def _propose(self, op: list, deadline_s: float = 5.0):
        """Append ``op`` to the log and drive replication until it
        commits (majority-ack) — only then is the applied result
        released to the caller. Raises NotLeader off-leader and
        Unavailable when the quorum cannot be reached in time (writes
        park; the entry may still commit later, which is why every
        state verb is either idempotent or gap-burn-safe)."""
        with self._lock:
            if self._role != ROLE_LEADER:
                raise NotLeader("state write on non-leader",
                                self._leader_url_locked(), self._term)
            term = self._term
            self._log.append((term, op))
            idx = self._last_index()
            self._wal.entry(idx, term, op)
            if len(self._peers) == 1:
                self._commit = idx
                self._apply_locked()
                return self._result_of_locked(idx, term)
        end = time.monotonic() + deadline_s
        while time.monotonic() < end and not self._stop.is_set():
            self._replication_round()
            with self._lock:
                if self._commit >= idx:
                    return self._result_of_locked(idx, term)
                if self._role != ROLE_LEADER or self._term != term:
                    raise NotLeader("deposed mid-propose",
                                    self._leader_url_locked(),
                                    self._term)
            time.sleep(0.02)
        raise Unavailable(
            f"state quorum unavailable ({op[0]}); writes park")

    def _leader_url_locked(self) -> str | None:
        if self._leader is None:
            return None
        return self._peers.get(self._leader) or None

    def _result_of_locked(self, idx: int, term: int):
        """The applied result of OUR proposal at ``idx`` — judged by
        the (term, result) record, not the log (which may already be
        compacted past idx): a differing term means our entry was
        overwritten before committing and the caller must re-resolve."""
        rec = self._results.get(idx)
        if rec is None or rec[0] != term:
            raise NotLeader("deposed before commit",
                            self._leader_url_locked(), self._term)
        return rec[1]

    # ------------- apply (the deterministic state machine) -------------

    def _apply_locked(self) -> None:
        while self._applied < self._commit:
            self._applied += 1
            e_term, op = self._log[self._applied - self._floor_idx - 1]
            self._results[self._applied] = (e_term, self._apply_op(op))
            if len(self._results) > 4096:
                for k in sorted(self._results)[:-2048]:
                    self._results.pop(k, None)
        self._maybe_compact_locked()

    def _sm_dump_locked(self) -> dict:
        return {"rv": self._sm_rv,
                "ring": {"epoch": self._sm_ring["epoch"],
                         "slots": list(self._sm_ring["slots"])},
                "sched_ring": {
                    "epoch": self._sm_sched_ring["epoch"],
                    "slots": list(self._sm_sched_ring["slots"])},
                "leases": self._sm_leases.dump()}

    def _sm_load_locked(self, state: dict) -> None:
        self._sm_rv = int(state["rv"])
        self._sm_ring = {"epoch": int(state["ring"]["epoch"]),
                         "slots": list(state["ring"]["slots"])}
        # absent in pre-scale-out snapshots/WALs: default to empty
        sr = state.get("sched_ring") or {"epoch": 0, "slots": []}
        self._sm_sched_ring = {"epoch": int(sr["epoch"]),
                               "slots": list(sr["slots"])}
        self._sm_leases.restore(state["leases"])

    def _install_snapshot_locked(self, snap: dict,
                                 persist: bool = True) -> None:
        """Replace everything at or below the snapshot index with the
        snapshot's state machine: the lagging-follower catch-up path
        (a leader whose log no longer reaches back that far) and the
        WAL-replay boot path."""
        idx, term = int(snap["idx"]), int(snap["term"])
        self._sm_load_locked(snap["state"])
        self._floor_idx, self._floor_term = idx, term
        self._log = []
        self._commit = self._applied = idx
        self._results.clear()
        if persist:
            self._wal_rewrite_locked()

    def _maybe_compact_locked(self) -> None:
        """Drop applied log entries behind a snapshot once the log
        outgrows the threshold — without this, one entry per rv the
        whole fleet ever drew accumulates in memory and in the WAL
        forever. Safe at any point ≤ applied: the state machine IS the
        summary, and a peer needing older entries gets the snapshot
        installed instead."""
        if len(self._log) <= self._compact_threshold:
            return
        k = self._applied
        if k <= self._floor_idx:
            return
        self._floor_term = self._term_at(k)
        del self._log[:k - self._floor_idx]
        self._floor_idx = k
        self._wal_rewrite_locked()

    def _wal_rewrite_locked(self) -> None:
        snap = {"idx": self._floor_idx, "term": self._floor_term,
                "state": self._sm_dump_locked()}
        entries = [(self._floor_idx + 1 + j, t, op)
                   for j, (t, op) in enumerate(self._log)]
        self._wal.rewrite(self._term, self._voted_for, snap, entries)

    def _apply_op(self, op: list):
        verb = op[0]
        if verb == "noop":
            return None
        if verb == "rv.next":
            self._sm_rv += 1
            return self._sm_rv
        if verb == "rv.advance_to":
            if int(op[1]) > self._sm_rv:
                self._sm_rv = int(op[1])
            return self._sm_rv
        if verb == "leases.update":
            return self._sm_leases.update(op[1], op[2])
        if verb == "ring.set":
            ring, expect = op[1], int(op[2])
            if self._sm_ring["epoch"] != expect:
                return False
            self._sm_ring = {"epoch": int(ring["epoch"]),
                             "slots": list(ring["slots"])}
            return True
        if verb == "sched_ring.set":
            ring, expect = op[1], int(op[2])
            if self._sm_sched_ring["epoch"] != expect:
                return False
            self._sm_sched_ring = {"epoch": int(ring["epoch"]),
                                   "slots": list(ring["slots"])}
            return True
        raise ValueError(f"unknown replicated op {verb!r}")

    # ------------- Raft RPCs (served over /call) -------------

    def replica_request_vote(self, term: int, candidate: str,
                             last_idx: int, last_term: int) -> dict:
        with self._lock:
            if term > self._term:
                self._become_follower_locked(term)
            granted = False
            if term == self._term \
                    and self._voted_for in (None, candidate):
                my_last_idx = self._last_index()
                my_last_term = self._term_at(my_last_idx)
                if (last_term, last_idx) >= (my_last_term, my_last_idx):
                    self._voted_for = candidate
                    self._wal.hard_state(self._term, self._voted_for)
                    granted = True
                    # granting a vote IS leader contact: don't campaign
                    # against the candidate we just endorsed
                    self._last_heard = time.monotonic()
            return {"term": self._term, "granted": granted}

    def replica_append_entries(self, term: int, leader: str,
                               prev_idx: int, prev_term: int,
                               entries: list, commit: int,
                               soft: dict | None = None,
                               snapshot: dict | None = None) -> dict:
        with self._lock:
            if term < self._term:
                return {"term": self._term, "ok": False, "match": 0}
            self._become_follower_locked(term, leader)
            if soft:
                # registry gossip: the follower mirrors the leader's
                # soft state so a failover starts from a warm map
                self._shards = {n: dict(s)
                                for n, s in soft.get("shards",
                                                     {}).items()}
                self._routers = {n: dict(r)
                                 for n, r in soft.get("routers",
                                                      {}).items()}
                self._relays = {n: dict(r)
                                for n, r in soft.get("relays",
                                                     {}).items()}
                self._schedulers = {n: dict(r)
                                    for n, r in soft.get("schedulers",
                                                         {}).items()}
            if snapshot is not None \
                    and int(snapshot["idx"]) > self._commit:
                # the leader compacted past our log: install its state
                # machine wholesale (committed prefixes are immutable,
                # so jumping to the snapshot can never un-commit)
                self._install_snapshot_locked(snapshot)
            if prev_idx > self._last_index() or (
                    prev_idx > self._floor_idx
                    and self._term_at(prev_idx) != prev_term):
                return {"term": self._term, "ok": False,
                        "match": min(self._last_index(),
                                     max(prev_idx - 1, 0))}
            # prev_idx at or below our floor: that prefix is committed
            # and compacted here, hence identical — append the part of
            # the batch above the floor
            for e in entries:
                i = int(e["i"])
                if i <= self._floor_idx:
                    continue
                if i <= self._last_index():
                    if self._term_at(i) == int(e["t"]):
                        continue          # already have it
                    # conflicting suffix: a deposed leader's entries
                    # are overwritten (they never committed)
                    del self._log[i - self._floor_idx - 1:]
                    self._wal.truncate(i - 1)
                self._log.append((int(e["t"]), list(e["op"])))
                self._wal.entry(i, int(e["t"]), list(e["op"]))
            new_commit = min(int(commit), self._last_index())
            if new_commit > self._commit:
                self._commit = new_commit
                self._apply_locked()
            return {"term": self._term, "ok": True,
                    "match": prev_idx + len(entries)}

    # ------------- read guards -------------

    def _read_guard(self, linearizable: bool = False) -> None:
        with self._lock:
            if self._role == ROLE_LEADER:
                if len(self._peers) == 1:
                    return
                now = time.monotonic()
                fresh = sum(1 for t in self._last_ack.values()
                            if now - t <= self._lease_s)
                if fresh + 1 >= self._majority():
                    return
                raise Unavailable(
                    "state leader lost quorum contact; reads and "
                    "writes park until the lease renews")
            if not linearizable \
                    and time.monotonic() - self._last_heard \
                    <= self._lease_s:
                return       # follower read inside the staleness bound
            raise NotLeader(
                "fencing reads are leader-only" if linearizable
                else "follower past the leader-lease staleness bound",
                self._leader_url_locked(), self._term)

    # ------------- public verbs (StateCore's surface) -------------

    def fabric_register_shard(self, name: str, url: str,
                              kinds: list | None = None,
                              pid: int | None = None) -> dict:
        self._require_leader()
        with self._lock:
            self._shards[name] = {"name": name, "url": url,
                                  "kinds": list(kinds or []),
                                  "pid": pid, "ts": time.time()}
            return {"ring": dict(self._sm_ring)}

    def fabric_register_router(self, name: str, url: str,
                               pid: int | None = None) -> dict:
        self._require_leader()
        with self._lock:
            self._routers[name] = {"name": name, "url": url,
                                   "pid": pid, "ts": time.time()}
            return {"ok": True}

    def fabric_register_relay(self, info: dict) -> dict:
        self._require_leader()
        with self._lock:
            rec = dict(info)
            rec["ts"] = time.time()
            self._relays[rec["name"]] = rec
            return {"ok": True}

    def fabric_register_scheduler(self, name: str, url: str = "",
                                  pid: int | None = None) -> dict:
        """Scheduler-replica heartbeat: soft registry (gossiped like
        relays), but the returned slice ring is log-applied state."""
        self._require_leader()
        with self._lock:
            self._schedulers[name] = {"name": name, "url": url,
                                      "pid": pid, "ts": time.time()}
            return {"ring": {
                "epoch": self._sm_sched_ring["epoch"],
                "slots": list(self._sm_sched_ring["slots"])}}

    def fabric_unregister_scheduler(self, name: str) -> dict:
        self._require_leader()
        with self._lock:
            self._schedulers.pop(name, None)
            return {"ok": True}

    def fabric_schedulers(self) -> dict:
        self._read_guard()
        with self._lock:
            return {n: dict(s) for n, s in self._schedulers.items()}

    def _require_leader(self) -> None:
        with self._lock:
            if self._role != ROLE_LEADER:
                raise NotLeader("registration on non-leader",
                                self._leader_url_locked(), self._term)

    def fabric_shards(self) -> dict:
        self._read_guard()
        with self._lock:
            return {n: dict(s) for n, s in self._shards.items()}

    def fabric_topology(self) -> dict:
        self._read_guard()
        now = time.time()
        with self._lock:
            relays = [dict(r) for r in self._relays.values()
                      if now - r["ts"] <= RELAY_TTL_S]
            scheds = {n: dict(s) for n, s in self._schedulers.items()
                      if now - s["ts"] <= SCHEDULER_TTL_S}
            return {"routers": [dict(r)
                                for r in self._routers.values()],
                    "relays": relays,
                    "shards": {n: dict(s)
                               for n, s in self._shards.items()},
                    "schedulers": scheds,
                    "ring_epoch": self._sm_ring["epoch"],
                    "sched_ring_epoch": self._sm_sched_ring["epoch"],
                    "replicas": self._replica_rows_locked()}

    def _replica_rows_locked(self) -> list[dict]:
        rows = [{"name": self.name,
                 "url": self._peers.get(self.name, ""),
                 "role": self._role, "term": self._term,
                 "log_index": self._last_index(),
                 "commit_index": self._commit}]
        if self._role == ROLE_LEADER:
            now = time.monotonic()
            for p in self._other_peers():
                rows.append({
                    "name": p, "url": self._peers.get(p, ""),
                    "role": ROLE_FOLLOWER
                    if now - self._last_ack.get(p, 0.0)
                    <= self._lease_s else "unreachable",
                    "term": self._term,
                    "log_index": self._match_idx.get(p, 0),
                    "commit_index": min(self._match_idx.get(p, 0),
                                        self._commit)})
        return rows

    def fabric_ring(self) -> dict:
        self._read_guard()
        with self._lock:
            return {"epoch": self._sm_ring["epoch"],
                    "slots": list(self._sm_ring["slots"])}

    def fabric_set_ring(self, ring: dict, expect_epoch: int) -> bool:
        return self._propose(["ring.set", dict(ring),
                              int(expect_epoch)])

    def fabric_sched_ring(self) -> dict:
        self._read_guard()
        with self._lock:
            return {"epoch": self._sm_sched_ring["epoch"],
                    "slots": list(self._sm_sched_ring["slots"])}

    def fabric_set_sched_ring(self, ring: dict,
                              expect_epoch: int) -> bool:
        return self._propose(["sched_ring.set", dict(ring),
                              int(expect_epoch)])

    def fabric_replica_status(self) -> dict:
        """Leader discovery + /debug surface: served by EVERY role with
        no staleness guard — a caller must be able to ask a confused
        replica who it thinks leads."""
        with self._lock:
            return {"name": self.name, "role": self._role,
                    "term": self._term, "leader": self._leader,
                    "leader_url": self._leader_url_locked(),
                    "log_index": self._last_index(),
                    "commit_index": self._commit,
                    "compact_floor": self._floor_idx,
                    "applied_rv": self._sm_rv,
                    "replicas": dict(self._peers)}

    # ------------- fleet surface -------------

    def get_journal_stats(self) -> dict:
        with self._lock:
            return {"rv": self._sm_rv, "capacity": 0,
                    "wal": self._wal.path is not None, "kinds": {},
                    "shards": {n: {"kinds": s["kinds"], "depth": 0,
                                   "compacted_rv": 0, "commits": 0,
                                   "rv": 0}
                               for n, s in self._shards.items()}}

    def healthz(self) -> tuple[int, str]:
        """200-with-role: a follower is healthy, not degraded — only a
        replica that can neither lead nor hear a leader reports 503."""
        with self._lock:
            role, term = self._role, self._term
            heard = time.monotonic() - self._last_heard
        if role == ROLE_LEADER or heard <= max(self._eto) * 2:
            return 200, f"ok role={role} term={term}"
        return 503, f"no leader contact role={role} term={term}"

    def extra_metrics_text(self) -> str:
        from kubernetes_tpu.telemetry.fleet import state_metrics_text

        return state_metrics_text(self)


class _ReplicaRv:
    """The ``rv.*`` verb surface: next/advance_to are replicated ops,
    last is a leader-lease read (resume checks and sync markers compare
    against it — a stale-low answer would spuriously 410 a fresh
    cursor, so it rides the leader lease, not follower gossip)."""

    __slots__ = ("_r",)

    def __init__(self, replica: StateReplica):
        self._r = replica

    def next(self) -> int:
        return self._r._propose(["rv.next"])

    def advance_to(self, rv: int) -> int:
        return self._r._propose(["rv.advance_to", int(rv)])

    def last(self) -> int:
        self._r._read_guard(linearizable=True)
        with self._r._lock:
            return self._r._sm_rv


class _ReplicaLeases:
    """The ``leases.*`` surface. ``epoch_of`` is LEADER-ONLY: fencing
    is the one read a lagging follower must never answer — an epoch one
    commit stale would let a deposed scheduler's write through."""

    __slots__ = ("_r",)

    def __init__(self, replica: StateReplica):
        self._r = replica

    def get(self, name: str):
        self._r._read_guard(linearizable=True)
        return self._r._sm_leases.get(name)

    def epoch_of(self, name: str) -> int:
        self._r._read_guard(linearizable=True)
        return self._r._sm_leases.epoch_of(name)

    def update(self, lease, expect_holder=None) -> bool:
        return self._r._propose(["leases.update", lease, expect_holder])


# --------------------------------------------------------------------------
# the client: leader-routing facade over the replica set
# --------------------------------------------------------------------------


class ReplicaClient:
    """RemoteHub-shaped client for a replica set: caches the leader,
    follows ``NotLeader`` redirect hints, rotates through candidates
    during elections, and discovers the full replica set from any
    member. ``ProcShardHub``/``ClusterClient``/electors use it exactly
    like a ``RemoteHub`` pointed at a single StateCore."""

    def __init__(self, urls, timeout: float = 10.0,
                 client_factory=None,
                 redirect_deadline_s: float = 8.0):
        from kubernetes_tpu.hubclient import (
            RemoteHub,
            _RemoteLeases,
            _RemoteNamespace,
        )

        if isinstance(urls, str):
            urls = urls.split(",")
        self._urls = [u.strip().rstrip("/") for u in urls if u.strip()]
        if not self._urls:
            raise ValueError("ReplicaClient needs at least one URL")
        self._factory = client_factory or (
            lambda url: RemoteHub(url, timeout=timeout,
                                  retry_deadline=1.0))
        self._lock = threading.Lock()
        self._clients: dict[str, object] = {}
        self._leader_url: str | None = None
        self._deadline = redirect_deadline_s
        self.rv = _RemoteNamespace(self._call, "rv")
        self.leases = _RemoteLeases(self._call, "leases")

    def _client(self, url: str):
        with self._lock:
            c = self._clients.get(url)
            if c is None:
                c = self._clients[url] = self._factory(url)
            return c

    def _learn(self, urls) -> None:
        with self._lock:
            for u in urls:
                u = u.strip().rstrip("/")
                if u and u not in self._urls:
                    self._urls.append(u)

    def _call(self, method: str, *args):
        from kubernetes_tpu.hub import NotLeader as _NL

        end = time.monotonic() + self._deadline
        last_err: Exception | None = None
        i = 0
        while True:
            with self._lock:
                url = self._leader_url or self._urls[i % len(self._urls)]
            try:
                return self._client(url)._call(method, *args)
            except _NL as e:
                hint = e.leader_url.rstrip("/") if e.leader_url else None
                with self._lock:
                    if hint and hint != url:
                        self._leader_url = hint
                        if hint not in self._urls:
                            self._urls.append(hint)
                    else:
                        self._leader_url = None
                        i += 1
                last_err = e
            except Unavailable as e:
                with self._lock:
                    if self._leader_url == url:
                        self._leader_url = None
                i += 1
                last_err = e
            if time.monotonic() >= end:
                raise Unavailable(
                    f"{method}: no state leader reachable "
                    f"({last_err!r})") from None
            time.sleep(0.05)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def proxy(*args, _m=name):
            return self._call(_m, *args)

        proxy.__name__ = name
        return proxy

    # ------------- discovery / status -------------

    def replica_status(self) -> list[dict]:
        """Per-replica status rows (direct, NOT leader-routed): each
        reachable member answers for itself — the /debug and storm
        surface for 'who leads, who lags, who is dead'."""
        rows: list[dict] = []
        with self._lock:
            urls = list(self._urls)
        for url in urls:
            try:
                st = self._client(url)._call("fabric_replica_status")
            except Exception as e:  # noqa: BLE001 — per-replica verdict
                rows.append({"url": url, "error": repr(e)})
                continue
            st = dict(st)
            st["url"] = url
            rows.append(st)
            self._learn(st.get("replicas", {}).values())
        return rows

    def leader_url(self, refresh: bool = False) -> str | None:
        """The cached (or freshly resolved) leader URL."""
        with self._lock:
            if self._leader_url is not None and not refresh:
                return self._leader_url
        for st in self.replica_status():
            if st.get("role") == ROLE_LEADER:
                with self._lock:
                    self._leader_url = st["url"]
                return st["url"]
            if st.get("leader_url"):
                with self._lock:
                    self._leader_url = st["leader_url"].rstrip("/")
                return self._leader_url
        return None

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def make_state_client(state_url: str, timeout: float = 10.0,
                      client_factory=None,
                      redirect_deadline_s: float = 8.0):
    """One constructor for both deployments: a comma-separated URL is a
    replica set (ReplicaClient); a single URL is the classic StateCore
    (plain RemoteHub). Every fabric component resolves its ``--state``
    argument through here."""
    if "," in state_url:
        return ReplicaClient(state_url, timeout=timeout,
                             client_factory=client_factory,
                             redirect_deadline_s=redirect_deadline_s)
    if client_factory is not None:
        return client_factory(state_url)
    from kubernetes_tpu.hubclient import RemoteHub

    return RemoteHub(state_url, timeout=timeout)
