"""Local fabric supervisor: spawn, watch, kill, and restart the shard
processes.

The process-mode deployment story on one host (the multi-host story is
the same commands run per machine — README "Multi-host deployment"):
``spawn_local_cluster(pod_shards=2)`` brings up

    state shard  ──  nodes / events / meta shards  ──  pods-0..N-1
                                │
                             router

each as its own OS process (``python -m kubernetes_tpu.fabric.proc``),
each announcing its bound port on stdout (``LISTENING <port>``) and
registering with the state shard. The supervisor's restart path reuses
a dead shard's WAL file and name — the restarted process replays its
journal, re-registers on a NEW port, and the router re-resolves it:
that sequence is exactly what the chaos battery ``kill -9``s to prove.

This is an orchestration convenience for benchmarks, tests, and the
``--fabric`` flag — not an init system: processes are daemonic to the
supervisor's host process and die with it.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class FabricProc:
    """One spawned fabric process: role, args, handle, bound port."""

    def __init__(self, name: str, role: str, args: list[str],
                 popen: subprocess.Popen, port: int):
        self.name = name
        self.role = role
        self.args = args
        self.popen = popen
        self.port = port

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def pid(self) -> int:
        return self.popen.pid

    def alive(self) -> bool:
        return self.popen.poll() is None


class FabricSupervisor:
    """Spawns fabric processes and keeps their handles; the chaos
    battery drives ``kill_shard``/``restart_shard`` against it."""

    def __init__(self, spawn_timeout_s: float = 20.0):
        self.procs: dict[str, FabricProc] = {}
        self._timeout = spawn_timeout_s

    def spawn(self, name: str, role: str, extra: list[str]) -> FabricProc:
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        args = [sys.executable, "-m", "kubernetes_tpu.fabric.proc",
                "--role", role, "--name", name, *extra]
        popen = subprocess.Popen(args, stdout=subprocess.PIPE,
                                 stderr=subprocess.DEVNULL,
                                 text=True, env=env, cwd=_REPO)
        port = self._await_port(popen, name)
        proc = FabricProc(name, role, extra, popen, port)
        self.procs[name] = proc
        return proc

    def _await_port(self, popen: subprocess.Popen, name: str) -> int:
        # readline() blocks, so the timeout must live on a reader
        # thread — a process that stays alive without ever binding
        # (wedged startup, runaway WAL replay) must fail the spawn
        # after spawn_timeout_s, not hang the caller forever
        import threading

        found: dict = {}

        def read() -> None:
            for line in popen.stdout:
                if line.startswith("LISTENING "):
                    found["port"] = int(line.split()[1])
                    return

        t = threading.Thread(target=read, daemon=True,
                             name=f"await-port-{name}")
        t.start()
        t.join(self._timeout)
        if "port" in found:
            return found["port"]
        if popen.poll() is not None:
            raise RuntimeError(
                f"fabric process {name!r} exited rc="
                f"{popen.returncode} before binding")
        popen.kill()
        raise RuntimeError(f"fabric process {name!r} never announced "
                           f"its port within {self._timeout}s")

    def wait_healthy(self, proc: FabricProc,
                     timeout_s: float = 15.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(proc.url + "/healthz",
                                            timeout=2.0) as resp:
                    if resp.status == 200:
                        return
            except OSError:
                pass
            time.sleep(0.05)
        raise RuntimeError(f"{proc.name} never answered /healthz")

    def kill_shard(self, name: str, sig: int = signal.SIGKILL) -> int:
        """The chaos verb: SIGKILL by default — no drain, no WAL
        close, exactly the failure the replay path must absorb."""
        proc = self.procs[name]
        pid = proc.pid
        proc.popen.send_signal(sig)
        proc.popen.wait(timeout=10)
        return pid

    def restart_shard(self, name: str) -> FabricProc:
        """Re-spawn a dead shard with its original args (same WAL,
        same name, new port): WAL replay + re-registration heal the
        fabric without touching any other process."""
        old = self.procs[name]
        if old.alive():
            raise RuntimeError(f"{name} is still alive; kill it first")
        proc = self.spawn(name, old.role, old.args)
        self.wait_healthy(proc)
        return proc

    def stop(self) -> None:
        for proc in self.procs.values():
            if proc.alive():
                proc.popen.terminate()
        deadline = time.monotonic() + 5.0
        for proc in self.procs.values():
            try:
                proc.popen.wait(timeout=max(
                    0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.popen.kill()


class LocalCluster:
    """A running process-mode fabric: the supervisor plus the resolved
    URLs a client needs. ``state_url`` is the comma-joined replica set
    when the state core is replicated (every fabric client accepts the
    comma form); ``state_urls`` lists the members individually."""

    def __init__(self, sup: FabricSupervisor, state_url: str,
                 router_url: str, pod_shards: list[str],
                 state_urls: list[str] | None = None):
        self.sup = sup
        self.state_url = state_url
        self.router_url = router_url
        self.pod_shards = pod_shards
        self.state_urls = state_urls or [state_url]

    def shard_names(self) -> list[str]:
        return [n for n, p in self.sup.procs.items()
                if p.role == "shard"]

    def state_leader(self, timeout_s: float = 15.0) -> str:
        """Name of the state replica currently leading (replicated
        clusters only) — the chaos storms' kill target."""
        from kubernetes_tpu.fabric.replica import ReplicaClient

        client = ReplicaClient(self.state_urls)
        try:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                for st in client.replica_status():
                    if st.get("role") == "leader":
                        return st["name"]
                time.sleep(0.1)
            raise RuntimeError("no state leader elected in time")
        finally:
            client.close()

    def stop(self) -> None:
        self.sup.stop()


def _free_port() -> int:
    """Pre-assign a listen port (the replica peer map must be known
    before any replica starts — etcd's static bootstrap). The tiny
    race between close and rebind is acceptable on a lab host."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_local_cluster(pod_shards: int = 2,
                        wal_dir: str | None = None,
                        journal_capacity: int = 65536,
                        wal_codec: str = "bin1",
                        kind_shards: bool = True,
                        router: bool = True,
                        state_replicas: int = 1) -> LocalCluster:
    """Bring up the whole fabric on this host. ``kind_shards=False``
    collapses nodes/events/meta into pods-0 (the minimal two-process
    cluster the tier-1 smoke uses: state + one all-kinds shard).
    ``state_replicas=3`` runs the REPLICATED state core: three replica
    processes with pinned ports and per-replica log WALs; a ``kill
    -9``'d member restarts onto the same port and catches up from the
    leader's log."""
    sup = FabricSupervisor()
    pod_names = [f"pods-{i}" for i in range(pod_shards)]
    try:
        if state_replicas > 1:
            ports = [_free_port() for _ in range(state_replicas)]
            names = [f"state-{i}" for i in range(state_replicas)]
            peers = ",".join(f"{n}=http://127.0.0.1:{p}"
                             for n, p in zip(names, ports))
            state_procs = []
            for n, p in zip(names, ports):
                extra = ["--port", str(p), "--replica-id", n,
                         "--peers", peers,
                         "--pod-shards", ",".join(pod_names)]
                if wal_dir:
                    os.makedirs(wal_dir, exist_ok=True)
                    extra += ["--wal",
                              os.path.join(wal_dir, f"{n}.wal")]
                state_procs.append(sup.spawn(n, "state", extra))
            for proc in state_procs:
                sup.wait_healthy(proc)
            state_urls = [proc.url for proc in state_procs]
            state_url = ",".join(state_urls)
            # shards registering before the first election would burn
            # their redirect budget: wait for a leader once, here
            LocalCluster(sup, state_url, "", pod_names,
                         state_urls).state_leader()
        else:
            state = sup.spawn("state", "state",
                              ["--pod-shards", ",".join(pod_names)])
            sup.wait_healthy(state)
            state_urls = [state.url]
            state_url = state.url

        def shard_args(name: str, kinds: str) -> list[str]:
            extra = ["--state", state_url, "--kinds", kinds,
                     "--journal-capacity", str(journal_capacity),
                     "--wal-codec", wal_codec]
            if wal_dir:
                os.makedirs(wal_dir, exist_ok=True)
                extra += ["--wal", os.path.join(wal_dir, f"{name}.wal")]
            return extra

        shard_procs = []
        if kind_shards:
            shard_procs.append(sup.spawn(
                "nodes", "shard", shard_args("nodes", "nodes")))
            shard_procs.append(sup.spawn(
                "events", "shard", shard_args("events", "events")))
            shard_procs.append(sup.spawn(
                "meta", "shard", shard_args("meta", "*")))
            pod_kinds = "pods"
        else:
            # the minimal cluster: pods-0 owns everything
            pod_kinds = "pods,nodes,events,*"
        for name in pod_names:
            shard_procs.append(sup.spawn(
                name, "shard", shard_args(name, pod_kinds)))
        for p in shard_procs:
            sup.wait_healthy(p)
        router_url = ""
        if router:
            r = sup.spawn("router-0", "router", ["--state", state_url])
            sup.wait_healthy(r)
            router_url = r.url
        return LocalCluster(sup, state_url, router_url, pod_names,
                            state_urls)
    except BaseException:
        sup.stop()
        raise
