"""ShardedHub: the hub sharded by kind + namespace-hash, one API.

The apiserver/etcd analog outgrew one lock and one WAL: every mutation
of every kind serialized through a single ``Hub``. The fabric shards it
the way the real control plane does (etcd per resource group,
apiserver request fan-out):

* **by kind** — nodes, events, and "meta" (every other non-pod kind)
  each get their own shard: a full :class:`~kubernetes_tpu.hub.Hub`
  with its own lock, journal rings, and WAL file, so node heartbeats
  never contend with event recording or claim churn;
* **by namespace-hash within the pod kind** — pods (the hot kind) hash
  across ``pod_shards`` shards by ``crc32(namespace)``, a deterministic
  mapping (NOT Python's randomized ``hash``) so a restarted hub replays
  each shard's WAL into the same layout.

One **revision space** spans all shards: a shared allocator stamps
every commit, so resume points travel freely — a client that saw rv N
on a pod event can resume ANY kind's watch at N, exactly as against the
single hub. Each shard's journal retains its kinds' complete suffix
above its own watermark (per-kind rv gaps were already the journal's
contract). Cross-shard pod watches register on every pod shard; replay
is rv-consistent per shard, per-object ordering holds globally because
a pod lives on exactly one shard.

Fencing is hub-wide: all shards share one ``LeaseStore``, so a deposed
leader's epoch is stale on every shard at once.

The router preserves the single-hub surface — ``HubServer(ShardedHub())``
and every ``RemoteHub`` client work unchanged.
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Optional

from kubernetes_tpu.hub import Hub, NotFound
from kubernetes_tpu.hubserver import WATCH_KINDS
from kubernetes_tpu.leaderelection import LeaseStore
from kubernetes_tpu.storage import RvTooOld


class _RvAllocator:
    """The shared revision counter: one monotonic space across shards.
    Its own lock (never taken while holding another allocator's — it IS
    the innermost lock: shards call ``next()`` under their shard lock,
    and the allocator takes nothing further)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.last = 0

    def next(self) -> int:
        with self._lock:
            self.last += 1
            return self.last

    def advance_to(self, rv: int) -> None:
        with self._lock:
            if rv > self.last:
                self.last = rv


class _ShardHub(Hub):
    """One shard: a full Hub drawing revisions from the shared
    allocator. It carries every store (empty ones cost nothing) so the
    router can forward ANY hub method to the owning shard without
    per-method glue; only its assigned kinds ever populate."""

    def __init__(self, name: str, alloc: _RvAllocator,
                 journal_capacity: int, wal_path: str | None):
        self.shard_name = name
        # trace stamps name the committing shard, so a joined timeline
        # attributes each commit to its shard without a lookup
        self.origin = name
        self._alloc = alloc
        self.commits = 0
        super().__init__(journal_capacity=journal_capacity,
                         wal_path=wal_path)

    def _next_rv(self) -> int:
        rv = self._alloc.next()
        self._last_rv = rv
        return rv

    def _newest_rv(self) -> int:
        # resume checks and sync markers speak the GLOBAL space: a
        # client's since_rv may have been minted by another shard
        return self._alloc.last

    def _commit(self, store, etype, old, new):
        self.commits += 1
        return super()._commit(store, etype, old, new)


# watch kind -> the by-kind shard that owns it ("pods" is special-cased
# onto the hashed shard set)
_NODE_KINDS = ("nodes",)
_EVENT_KINDS = ("events",)

# single-kind hub methods, routed whole to the owning shard
_NODE_METHODS = frozenset({"create_node", "update_node", "delete_node",
                           "get_node", "list_nodes", "watch_nodes"})
_EVENT_METHODS = frozenset({"record_event", "list_events",
                            "watch_events"})
# pod methods that carry the Pod object (route by namespace hash)
_POD_OBJ_METHODS = frozenset({"create_pod", "update_pod", "bind",
                              "patch_pod_condition"})
# pod methods that carry only a uid (route by probe — the uid index is
# per shard, and P dict probes beat a router-level mirror of every pod)
_POD_UID_METHODS = frozenset({"delete_pod", "get_pod",
                              "set_pod_claim_statuses",
                              "clear_nominated_node"})


class ShardedHub:
    """``Hub``-shaped router over kind shards + hashed pod shards.

    ``wal_dir`` (instead of the single hub's ``wal_path``) gives every
    shard its own WAL file under one directory; a restart replays each
    independently and the allocator resumes past the newest revision
    any shard saw."""

    def __init__(self, pod_shards: int = 4,
                 journal_capacity: int = 16384,
                 wal_dir: str | None = None) -> None:
        if pod_shards < 1:
            raise ValueError("pod_shards must be >= 1")
        if wal_dir:
            if os.path.isfile(wal_dir):
                # the single hub's --wal names a FILE; sharding needs a
                # directory (one WAL per shard), and a single-hub WAL
                # cannot replay into shards anyway — say so instead of
                # dying on makedirs' FileExistsError
                raise ValueError(
                    f"wal_dir {wal_dir!r} is an existing file: a "
                    "sharded hub needs a WAL directory (one file per "
                    "shard), and a single-hub WAL does not replay "
                    "into shards")
            os.makedirs(wal_dir, exist_ok=True)
        self._alloc = _RvAllocator()

        def mk(name: str) -> _ShardHub:
            wal = os.path.join(wal_dir, f"{name}.wal") if wal_dir \
                else None
            return _ShardHub(name, self._alloc, journal_capacity, wal)

        self._nodes_shard = mk("nodes")
        self._events_shard = mk("events")
        self._meta_shard = mk("meta")
        self._pod_shards = [mk(f"pods-{i}") for i in range(pod_shards)]
        self._shards: list[_ShardHub] = [
            self._nodes_shard, self._events_shard, self._meta_shard,
            *self._pod_shards]
        # WAL replay ran inside each shard's __init__ with original
        # revisions; the shared space resumes past the newest any saw
        self._alloc.advance_to(max(s._last_rv for s in self._shards))
        # ONE lease store: fencing epochs are a property of the control
        # plane, not of a shard — a deposed epoch is stale everywhere
        self.leases = LeaseStore()
        for s in self._shards:
            s.leases = self.leases
        # ONE slice board for the same reason: the scheduler-replica
        # slice map partitions the whole pending-pod space, so every
        # shard must serve (and fence against) the same ring
        self.slices = self._meta_shard.slices
        for s in self._shards:
            s.slices = self.slices

    # ------------- revision space -------------

    @property
    def current_rv(self) -> int:
        return self._alloc.last

    def _newest_rv(self) -> int:
        return self._alloc.last

    # ------------- routing -------------

    def _pod_shard(self, namespace: str) -> _ShardHub:
        h = zlib.crc32(namespace.encode("utf-8"))
        return self._pod_shards[h % len(self._pod_shards)]

    def _pod_shard_of_uid(self, uid: str) -> Optional[_ShardHub]:
        for s in self._pod_shards:
            if s.get_pod(uid) is not None:
                return s
        return None

    def __getattr__(self, name: str):
        # single-shard methods forward whole; the meta shard owns every
        # kind the tables above don't claim. Defined-on-class methods
        # (pods, watches, stats) never reach here.
        if name in _NODE_METHODS:
            return getattr(self._nodes_shard, name)
        if name in _EVENT_METHODS:
            return getattr(self._events_shard, name)
        if not name.startswith("_") and hasattr(Hub, name):
            return getattr(self._meta_shard, name)
        raise AttributeError(name)

    # ------------- pods (hashed across shards) -------------

    def create_pod(self, pod) -> None:
        self._pod_shard(pod.metadata.namespace).create_pod(pod)

    def update_pod(self, pod) -> None:
        self._pod_shard(pod.metadata.namespace).update_pod(pod)

    def bind(self, pod, node_name: str, epoch: int | None = None,
             lease_name: str = "kube-scheduler") -> None:
        self._pod_shard(pod.metadata.namespace).bind(
            pod, node_name, epoch, lease_name)

    def patch_pod_condition(self, pod, condition,
                            nominated_node: str | None = None,
                            epoch: int | None = None,
                            lease_name: str = "kube-scheduler") -> None:
        self._pod_shard(pod.metadata.namespace).patch_pod_condition(
            pod, condition, nominated_node, epoch, lease_name)

    def delete_pod(self, uid: str, epoch: int | None = None,
                   lease_name: str = "kube-scheduler") -> None:
        s = self._pod_shard_of_uid(uid)
        if s is None:
            raise NotFound(f"Pod {uid}")
        # a concurrent delete between probe and call re-raises NotFound
        # from the shard — same verdict the single hub gives
        s.delete_pod(uid, epoch, lease_name)

    def delete_pods(self, uids: list[str], epoch: int | None = None,
                    lease_name: str = "kube-scheduler") -> list[str]:
        """Batched eviction wave, per owning shard: uids group by the
        shard that holds them (probe like delete_pod), one wave per
        shard. Must be explicit — __getattr__ would otherwise forward
        the whole wave to the META shard, which holds no pods, and the
        flush would strand every candidate."""
        by_shard: dict[int, tuple] = {}
        for uid in uids:
            s = self._pod_shard_of_uid(uid)
            if s is None:
                continue            # already gone: skipped like the Hub
            ent = by_shard.setdefault(id(s), (s, []))
            ent[1].append(uid)
        gone: list[str] = []
        for s, batch in by_shard.values():
            gone.extend(s.delete_pods(batch, epoch, lease_name))
        return gone

    def get_pod(self, uid: str):
        for s in self._pod_shards:
            p = s.get_pod(uid)
            if p is not None:
                return p
        return None

    def set_pod_claim_statuses(self, uid: str,
                               statuses: dict[str, str]) -> None:
        s = self._pod_shard_of_uid(uid)
        if s is not None:
            s.set_pod_claim_statuses(uid, statuses)

    def clear_nominated_node(self, uid: str, epoch: int | None = None,
                             lease_name: str = "kube-scheduler") -> None:
        s = self._pod_shard_of_uid(uid)
        if s is not None:
            s.clear_nominated_node(uid, epoch, lease_name)

    def list_pods(self) -> list:
        out: list = []
        for s in self._pod_shards:
            out.extend(s.list_pods())
        return out

    def watch_pods(self, h, replay: bool = True,
                   since_rv: int | None = None) -> int:
        """Cross-shard pod watch: register on EVERY pod shard.
        Registration+replay is atomic per shard (each under its shard
        lock), so per-object ordering is exact — a pod lives on one
        shard. Cross-object interleave across shards during replay is
        registration-ordered, which is all the informer contract
        promises for a LIST anyway. A compacted gap on ANY shard
        unregisters the rest and raises: a watch must never resume
        half-sharded."""
        registered: list[_ShardHub] = []
        cur = 0
        try:
            for s in self._pod_shards:
                cur = max(cur, s.watch_pods(h, replay=replay,
                                            since_rv=since_rv))
                registered.append(s)
        except RvTooOld:
            for s in registered:
                s.unwatch(h)
            raise
        return cur

    def unwatch(self, h) -> None:
        for s in self._shards:
            s.unwatch(h)

    # ------------- incremental LIST (drift sentinel) -------------

    def list_changes(self, since_rv: int,
                     kinds: tuple = ("pods", "nodes")) -> dict:
        """Merged across the owning shards; any shard's too-old verdict
        is the whole answer's (a partial incremental diff would hide
        the unresumable shard's history).

        The consistency revision is read BEFORE the first shard scan:
        shards are read sequentially without a global lock, so a commit
        landing on an already-scanned shard mid-merge is absent from
        ``changes`` — advertising a later rv would make the caller's
        next resume skip it forever. Advertising the earlier rv instead
        means any such straggler (and any included event above it) is
        merely re-examined next pass, which is harmless."""
        rv0 = self._alloc.last
        merged: list[dict] = []
        for s in self._shards_for_kinds(kinds):
            res = s.list_changes(since_rv, kinds)
            if res.get("too_old"):
                return {"too_old": True,
                        "compacted_rv": res["compacted_rv"],
                        "rv": rv0}
            merged.extend(res["changes"])
        merged.sort(key=lambda c: c["rv"])
        return {"too_old": False, "rv": rv0, "changes": merged}

    def _shards_for_kinds(self, kinds) -> list[_ShardHub]:
        out: list[_ShardHub] = []
        for s in self._shards:
            if s in self._pod_shards:
                if "pods" in kinds:
                    out.append(s)
            elif s is self._nodes_shard:
                if any(k in _NODE_KINDS for k in kinds):
                    out.append(s)
            elif s is self._events_shard:
                if any(k in _EVENT_KINDS for k in kinds):
                    out.append(s)
            elif any(k not in _NODE_KINDS and k not in _EVENT_KINDS
                     and k != "pods" for k in kinds):
                out.append(s)
        return out

    # ------------- stats / lifecycle -------------

    def get_journal_stats(self) -> dict:
        """The single hub's shape (rv/capacity/wal/kinds) with per-kind
        stats merged across shards, plus a ``shards`` map for the
        hub_shard_* gauges and /debug/fabric."""
        kinds: dict = {}
        shards: dict = {}
        wal = False
        cap = 0
        for s in self._shards:
            st = s.get_journal_stats()
            wal = wal or st["wal"]
            cap = max(cap, st["capacity"])
            for kind, ks in st["kinds"].items():
                # a hashed kind ("pods") appears on several shards:
                # depth sums, watermark/last_rv take the max (the real
                # serviceable floor is the worst shard's, matching
                # list_changes' any-shard-too-old rule)
                agg = kinds.get(kind)
                if agg is None:
                    kinds[kind] = dict(ks)
                else:
                    agg["depth"] += ks["depth"]
                    agg["compacted_rv"] = max(agg["compacted_rv"],
                                              ks["compacted_rv"])
                    agg["last_rv"] = max(agg["last_rv"], ks["last_rv"])
            shards[s.shard_name] = {
                "kinds": sorted(st["kinds"]),
                "depth": sum(k["depth"] for k in st["kinds"].values()),
                "compacted_rv": max(
                    [k["compacted_rv"] for k in st["kinds"].values()],
                    default=0),
                "commits": s.commits,
                "rv": st["rv"],
            }
        return {"rv": self._alloc.last, "capacity": cap, "wal": wal,
                "kinds": kinds, "shards": shards}

    def shard_map(self) -> dict:
        """kind -> shard name (pods list every hashed shard): the
        /debug/fabric topology surface."""
        out = {kind: "meta" for kind in WATCH_KINDS}
        out["nodes"] = "nodes"
        out["events"] = "events"
        out["pods"] = [s.shard_name for s in self._pod_shards]
        return out

    def close(self) -> None:
        for s in self._shards:
            s.close()
