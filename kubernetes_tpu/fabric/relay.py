"""Watch relay tree: fan one upstream stream out to thousands of clients.

The hub (or a parent relay) should hold one socket per RELAY, not one
per kubelet-analog reflector — at 10k clients the difference is the
control plane staying up. A relay node:

* subscribes UPSTREAM once for its whole kind set (one multiplexed
  ``RemoteHub.watch_kinds`` connection riding the client's full
  resume/reconnect machinery — a cut between relay and hub costs one
  journal resume, invisible to every downstream subscriber);
* mirrors upstream state per kind (uid -> newest object) so it can
  serve downstream LIST replays itself, and keeps a bounded ring
  journal of recent events so downstream reconnects resume from their
  cursor (``since_rv``) without touching the hub;
* fans each event out to its subscribers through bounded queues with
  **slow-subscriber eviction**: a consumer that stops draining gets its
  stream cut (counted in ``slow_evictions``) instead of wedging the
  relay's memory — it reconnects and resumes, or relists through the
  relay's state mirror if its cursor fell off the ring. Backpressure
  never propagates upstream.

Continuity: if the relay's OWN upstream connection falls back to a full
relist (410: the hub compacted its gap), the reflector's relist diff
already re-emits exactly the missed adds/updates/deletes as ordinary
events, so subscribers stay continuous; the relay just resets its ring
at the new sync revision (``EventHandlers.on_sync``) because the events
replayed DURING a relist arrive in LIST order, not rv order, and must
not serve resumes.

:class:`RelayServer` is the HTTP face: hubserver's exact /watch wire
(kind/kinds/since_rv/replay + binary-codec negotiation) so any
``RemoteHub`` can point at a relay instead of the hub, ``POST /call``
proxied upstream (the relay is a read fan-out, writes pass through),
and token-gated ``/debug/fabric`` (topology, ring stats, per-subscriber
cursors). Relays chain: a level-2 relay's upstream URL is a level-1
relay's address.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.fabric.flowcontrol import (
    PRIORITY_SHED_FACTORS,
    watch_priority,
)
from kubernetes_tpu.hub import EventHandlers, TooManyRequests
from kubernetes_tpu.hubserver import (
    FRAMES_CONTENT_TYPE,
    make_stream_writers,
    parse_watch_query,
)
from kubernetes_tpu.storage import Journal, JournalEvent, RvTooOld


class Subscriber:
    """One downstream consumer: a bounded event queue + resume cursors.
    The producer (the relay's upstream reflector thread) appends and
    signals; the consumer (an HTTP handler thread, or the fanout
    smoke's in-process reflector) drains. ``evicted`` flips when the
    queue hit its bound — the consumer must tear down and reconnect.

    ``cursors`` is the PER-SOURCE-SHARD resume map (shard "" = an
    untagged single-hub upstream): through the fabric router, streams
    are rv-ordered per shard but not across shards, so the scalar
    ``cursor`` (max rv, kept for display and single-hub callers) is
    not a safe resume token on its own — reconnects hand ``cursors``
    back to :meth:`RelayCore.subscribe`."""

    __slots__ = ("kinds", "queue", "event", "cursor", "cursors",
                 "sync_shards", "evicted", "limit", "ident", "priority")

    def __init__(self, kinds: tuple[str, ...], limit: int,
                 cursor: int, ident: int, priority: str = "tenant"):
        self.kinds = kinds
        self.queue: deque = deque()
        self.event = threading.Event()
        self.cursor = cursor           # newest rv enqueued for us
        self.cursors: dict[str, int] = {}
        self.sync_shards: dict[str, int] = {}
        self.evicted = False
        self.limit = limit
        self.ident = ident
        # flow-control level (fabric.flowcontrol.watch_priority): under
        # global backlog pressure a subscriber's EFFECTIVE queue bound
        # is limit × its level's shed factor — best-effort cut first,
        # system/scheduler streams keep their full bound
        self.priority = priority

    def drain(self) -> list[dict]:
        """Consumer side: take everything queued (thread-safe against
        the producer's appends — deque ops are atomic)."""
        out = []
        while True:
            try:
                out.append(self.queue.popleft())
            except IndexError:
                return out


class RelayCore:
    """Transport-agnostic relay engine. ``RelayServer`` wraps it for
    HTTP subscribers; the fanout smoke attaches in-process subscribers
    directly (10k bounded queues need no 10k sockets)."""

    def __init__(self, upstream_url: str, kinds: tuple[str, ...] = ("pods",),
                 ring_capacity: int = 8192, queue_limit: int = 4096,
                 client_factory: Optional[Callable] = None,
                 timeout: float = 30.0,
                 watchdog: Optional[dict] = None,
                 backlog_limit: Optional[int] = None):
        from kubernetes_tpu.hubclient import RemoteHub

        self.upstream_url = upstream_url
        self.kinds = tuple(kinds)
        self.queue_limit = queue_limit
        # global backpressure threshold: when the summed downstream
        # backlog crosses it, eviction turns priority-aware (shed
        # factors) and NEW best-effort subscriptions answer 429.
        # None (default) keeps the legacy flat-eviction behavior.
        self.backlog_limit = backlog_limit
        self._ring_capacity = ring_capacity
        self._lock = threading.Lock()
        # ring journals PER SOURCE SHARD ("" = untagged single-hub
        # upstream): each shard's stream is rv-ordered, so each ring
        # serves gapless per-shard suffixes; a resume merges them
        self._rings: dict[str, Journal] = {}
        self._ring_rv: dict[str, int] = {}
        self._state: dict[str, dict[str, tuple]] = \
            {k: {} for k in self.kinds}
        self._subs: dict[str, list[Subscriber]] = \
            {k: [] for k in self.kinds}
        self._next_ident = 0
        self.last_rv = 0
        # ring integrity: appends must be rv-ascending PER SHARD for
        # changes_after to mean "everything after your cursor". An
        # upstream RELIST replays in LIST order — the moment an
        # out-of-order rv arrives that shard's ring is SUSPECT: resumes
        # answer RvTooOld (downstream relists from the state mirror,
        # which is safe) until the sync marker resets the rings. Events
        # still fan out live either way.
        self._ring_suspect: set[str] = set()
        self._synced = threading.Event()
        # counters (relay_* metrics / the fanout smoke's gates)
        self.slow_evictions = 0
        self.resume_serves = 0         # downstream (re)connects off the ring
        self.relist_serves = 0         # downstream LIST replays served
        self.events_in = 0
        self.events_out = 0
        # pressure-mode counters: evictions below the subscriber's full
        # bound (per priority level), and new subscriptions shed (429)
        self.pressure_evictions: dict[str, int] = {}
        self.subscriptions_shed = 0
        self._factory = client_factory or (
            lambda url: RemoteHub(url, timeout=timeout))
        self._handlers = {k: EventHandlers(
            on_event=self._make_on_event(k),
            on_sync=self._on_sync) for k in self.kinds}
        self.client = self._factory(upstream_url)
        # ONE upstream connection for the whole kind set — the property
        # the tree exists for: the hub's socket count scales with
        # relays, not with subscribers
        self.client.watch_kinds(self._handlers, replay=True)
        # liveness watchdog (ISSUE-13 satellite): probe the upstream on
        # a heartbeat deadline and auto-reparent through the served
        # topology map when it dies — cursor-carrying resume, so the
        # downstream subscribers never relist. Config keys:
        #   topology_url (required) — where to fetch the topology map
        #   deadline_s (default 3.0) — continuous-unhealthy budget
        #   interval_s (default 0.5) — probe cadence
        #   name (optional) — this relay's advertised name, excluded
        #     from its own candidate pool
        self.watchdog_reparents = 0
        self._wd = dict(watchdog) if watchdog else None
        self._wd_stop = threading.Event()
        self._wd_thread: Optional[threading.Thread] = None
        if self._wd is not None:
            self._wd_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="relay-watchdog")
            self._wd_thread.start()

    def _ring_for(self, shard: str) -> Journal:
        ring = self._rings.get(shard)
        if ring is None:
            ring = self._rings[shard] = Journal(
                capacity=self._ring_capacity)
        return ring

    # ------------- upstream side (reflector callbacks) -------------

    def _make_on_event(self, kind: str):
        def on_event(ev: JournalEvent) -> None:
            # trace propagation: this relay is one hop — every event
            # fans out (and journals) with the stamp's hop count bumped,
            # so a downstream consumer sees how many relays its copy
            # crossed. An unstamped event (pre-telemetry upstream, LIST
            # replay) stays unstamped: hop data degrades, events flow.
            trace = ev.trace.hop() if ev.trace is not None else None
            shard = ev.shard or ""
            d = {"type": ev.type, "rv": ev.rv, "kind": kind,
                 "old": ev.old, "new": ev.new, "trace": trace,
                 "sh": ev.shard}
            with self._lock:
                state = self._state[kind]
                if ev.type == "delete":
                    state.pop(ev.old.metadata.uid, None)
                else:
                    state[ev.new.metadata.uid] = (ev.rv, ev.new,
                                                  ev.shard)
                if ev.rv > self._ring_rv.get(shard, 0):
                    self._ring_for(shard).append(JournalEvent(
                        rv=ev.rv, kind=kind, type=ev.type,
                        old=ev.old, new=ev.new, trace=trace,
                        shard=ev.shard))
                    self._ring_rv[shard] = ev.rv
                else:
                    # LIST-ordered arrival (upstream relist replay):
                    # this shard's ring can't serve gapless resumes
                    self._ring_suspect.add(shard)
                if ev.rv > self.last_rv:
                    self.last_rv = ev.rv
                self.events_in += 1
                self._fan_out(kind, d)
        return on_event

    def _on_sync(self, rv: int, relisted: bool, shards=None) -> None:
        """Upstream sync marker. After a RELIST (first connect, or a
        410 fallback) the events just replayed arrived in LIST order —
        the rings cannot serve rv-ordered resumes from them, so each
        resets with its floor at its shard's sync revision (the
        marker's ``shards`` map; the global rv when untagged): a
        downstream cursor below the floor answers 410 and relists from
        the state mirror, which IS consistent. Journal resumes (the
        common reconnect) keep the rings."""
        with self._lock:
            floors = dict(shards or {})
            if relisted or self._ring_suspect:
                names = set(self._rings) | set(floors) or {""}
                for shard in names:
                    ring = Journal(capacity=self._ring_capacity)
                    ring.compact_floor = floors.get(shard, rv)
                    self._rings[shard] = ring
                    self._ring_rv[shard] = max(
                        self._ring_rv.get(shard, 0),
                        floors.get(shard, rv))
                self._ring_suspect.clear()
            else:
                # resume sync: rings keep serving; seed floors for any
                # shard this relay has never heard from, so its cursor
                # bookkeeping starts at the sync point
                for shard, srv in floors.items():
                    if shard not in self._rings:
                        ring = self._ring_for(shard)
                        ring.compact_floor = srv
                        self._ring_rv[shard] = srv
            if rv > self.last_rv:
                self.last_rv = rv
        self._synced.set()

    def _backlog(self) -> int:
        """Summed downstream backlog (caller holds the lock). A
        multi-kind subscriber counts once per kind — fine for a
        pressure heuristic, and it errs toward shedding sooner."""
        return sum(len(s.queue) for subs in self._subs.values()
                   for s in subs)

    def _under_pressure(self) -> bool:
        return self.backlog_limit is not None \
            and self._backlog() >= self.backlog_limit

    def _fan_out(self, kind: str, d: dict) -> None:
        # caller holds the lock; eviction rebuilds the list after the
        # sweep so iteration stays cheap (no copy per event)
        subs = self._subs[kind]
        sh = d.get("sh") or ""
        pressured = self._under_pressure()
        evicted_any = False
        for sub in subs:
            if sub.evicted:
                evicted_any = True
                continue
            limit = sub.limit
            if pressured:
                # priority-aware backpressure: under global backlog
                # pressure a subscriber's effective bound shrinks by
                # its level's shed factor — best-effort streams are cut
                # first while system/scheduler keep their full bound
                limit = max(1, int(limit * PRIORITY_SHED_FACTORS.get(
                    sub.priority, 0.25)))
            if len(sub.queue) >= limit:
                # backpressure verdict: this consumer stopped draining.
                # Cut it (it will reconnect-and-resume, or relist) —
                # never buffer unboundedly, never stall the siblings,
                # never push back upstream.
                sub.evicted = True
                sub.event.set()
                self.slow_evictions += 1
                if limit < sub.limit:
                    self.pressure_evictions[sub.priority] = \
                        self.pressure_evictions.get(sub.priority, 0) + 1
                evicted_any = True
                continue
            sub.queue.append(d)
            if d["rv"] > sub.cursor:
                sub.cursor = d["rv"]
            if d["rv"] > sub.cursors.get(sh, 0):
                sub.cursors[sh] = d["rv"]
            self.events_out += 1
            sub.event.set()
        if evicted_any:
            self._subs[kind] = [s for s in subs if not s.evicted]

    # ------------- downstream side -------------

    def subscribe(self, kinds: tuple[str, ...] | None = None,
                  since_rv: int | None = None, replay: bool = True,
                  queue_limit: int | None = None,
                  cursors: dict[str, int] | None = None,
                  priority: str = "tenant") -> Subscriber:
        """Register a downstream reflector. ``since_rv``/``cursors``
        resume off the relay's per-shard rings (RvTooOld when any
        needed cursor fell off its ring — the caller relists, exactly
        the hub's contract): each source shard's ring replays its own
        suffix after that shard's cursor (``cursors``; ``since_rv`` is
        the fallback for shards the caller has no cursor for, and the
        whole cursor against a single-hub upstream). Otherwise
        ``replay`` serves a LIST from the state mirror. Backlog and
        registration are atomic under the relay lock, so the
        subscriber's stream is gapless from its sync point."""
        kinds = tuple(kinds or self.kinds)
        for k in kinds:
            if k not in self._state:
                raise ValueError(f"relay does not carry kind {k!r}")
        if not self._synced.wait(timeout=30.0):
            raise RuntimeError("relay upstream never synced")
        resume = since_rv is not None or cursors is not None
        with self._lock:
            if priority == "best-effort" and self._under_pressure():
                # shed NEW best-effort subscriptions before degrading
                # existing streams: the 429 (with a hint) costs the
                # caller a redial, not a torn stream
                self.subscriptions_shed += 1
                raise TooManyRequests(
                    "relay under backlog pressure: best-effort "
                    "subscriptions shed", retry_after=1.0)
            sub = Subscriber(kinds, queue_limit or self.queue_limit,
                             self.last_rv, self._next_ident,
                             priority=priority)
            self._next_ident += 1
            # "complete through here", per shard, at registration time
            sub.sync_shards = {s: rv for s, rv in self._ring_rv.items()
                               if s}
            sub.cursors = dict(self._ring_rv)
            if resume:
                evs: list[JournalEvent] = []
                for shard, ring in self._rings.items():
                    cur = (cursors or {}).get(shard, since_rv) \
                        if shard else since_rv
                    if cur is None or shard in self._ring_suspect:
                        # no cursor for a shard that has history, or a
                        # mid-relist window (LIST-ordered ring): a
                        # gapless suffix cannot be promised — send this
                        # consumer to the state mirror instead
                        raise RvTooOld(kinds[0],
                                       cur if cur is not None else 0,
                                       self.last_rv)
                    evs.extend(ring.changes_after(kinds, cur))
                evs.sort(key=lambda e: e.rv)
                for ev in evs:
                    sub.queue.append({"type": ev.type, "rv": ev.rv,
                                      "kind": ev.kind, "old": ev.old,
                                      "new": ev.new, "trace": ev.trace,
                                      "sh": ev.shard})
                self.resume_serves += 1
            elif replay:
                # state-mirror LIST replay: objects, not events — the
                # commit stamps are gone, so these carry trace=None
                # (the documented degradation; nothing is withheld)
                for kind in kinds:
                    for rv, obj, shard in self._state[kind].values():
                        sub.queue.append({"type": "add", "rv": rv,
                                          "kind": kind, "old": None,
                                          "new": obj, "trace": None,
                                          "sh": shard})
                self.relist_serves += 1
            for kind in kinds:
                self._subs[kind].append(sub)
            if sub.queue:
                sub.event.set()
            return sub

    def unsubscribe(self, sub: Subscriber) -> None:
        with self._lock:
            for kind in sub.kinds:
                try:
                    self._subs[kind].remove(sub)
                except ValueError:
                    pass

    def subscriber_count(self) -> int:
        with self._lock:
            return len({id(s) for subs in self._subs.values()
                        for s in subs})

    def stats(self) -> dict:
        up = {}
        rs = getattr(self.client, "resilience_stats", None)
        if rs is not None:
            up = rs()
        with self._lock:
            return {"upstream": self.upstream_url,
                    "kinds": list(self.kinds),
                    "last_rv": self.last_rv,
                    "subscribers": len({id(s) for subs in
                                        self._subs.values()
                                        for s in subs}),
                    "slow_evictions": self.slow_evictions,
                    "resume_serves": self.resume_serves,
                    "relist_serves": self.relist_serves,
                    "events_in": self.events_in,
                    "events_out": self.events_out,
                    "backlog": self._backlog(),
                    "backlog_limit": self.backlog_limit,
                    "pressure_evictions": dict(self.pressure_evictions),
                    "subscriptions_shed": self.subscriptions_shed,
                    "watchdog_reparents": self.watchdog_reparents,
                    "upstream_client": up}

    def debug_state(self, max_subscribers: int = 200) -> dict:
        """/debug/fabric payload: topology + per-subscriber cursors."""
        with self._lock:
            subs = sorted({id(s): s for subs in self._subs.values()
                           for s in subs}.values(),
                          key=lambda s: s.ident)
            listed = [{"id": s.ident, "kinds": list(s.kinds),
                       "cursor": s.cursor,
                       "cursors": {sh: rv for sh, rv
                                   in s.cursors.items() if sh},
                       "queued": len(s.queue),
                       "priority": s.priority,
                       "evicted": s.evicted}
                      for s in subs[:max_subscribers]]
            ring = {}
            for shard, journal in self._rings.items():
                for k, v in journal.stats().items():
                    key = f"{shard}/{k}" if shard else k
                    ring[key] = {"depth": v["depth"],
                                 "compacted_rv": v["compacted_rv"]}
        st = self.stats()
        st.update({"ring": ring, "subscriber_cursors": listed,
                   "subscribers_total": st["subscribers"]})
        return st

    def _upstream_healthy(self) -> bool:
        """Two liveness signals, either one suffices to call the
        upstream alive: the multiplexed watch stream is up (the common
        case), or /healthz answers ok (covers the quiet-cluster window
        where a reconnect is still backing off)."""
        if getattr(self.client, "watches_healthy", True):
            return True
        try:
            with urllib.request.urlopen(
                    self.upstream_url.rstrip("/") + "/healthz",
                    timeout=1.0) as resp:
                return resp.status == 200
        except (OSError, urllib.error.URLError):
            return False

    def _watchdog_loop(self) -> None:
        deadline_s = float(self._wd.get("deadline_s", 3.0))
        interval_s = float(self._wd.get("interval_s", 0.5))
        down_since: Optional[float] = None
        while not self._wd_stop.wait(interval_s):
            try:
                if self._upstream_healthy():
                    down_since = None
                    continue
                now = time.monotonic()
                if down_since is None:
                    down_since = now
                if now - down_since < deadline_s:
                    continue
                if self._reparent_via_topology():
                    down_since = None
            except Exception:  # noqa: BLE001 — the watchdog must
                pass           # survive any transient topology error

    def _reparent_via_topology(self) -> bool:
        """Pick a new parent from the served topology map — a sibling
        relay carrying our kinds (the dead parent and ourselves
        excluded), else a router — and reparent with cursors: the move
        is a journal RESUME, downstream subscribers keep streaming with
        zero relists."""
        from kubernetes_tpu.fabric.router import fetch_topology

        topo = fetch_topology(self._wd["topology_url"], timeout=3.0)
        relays = topo.get("relays", [])
        dead = self.upstream_url.rstrip("/")
        exclude = {r.get("name") for r in relays
                   if r.get("url", "").rstrip("/") == dead}
        my_name = self._wd.get("name")
        if my_name:
            exclude.add(my_name)
            # exclude our own DESCENDANTS too: re-homing onto a relay
            # whose parent chain leads back here would close a watch
            # cycle with no path to the hub — and because the stream to
            # the descendant stays "healthy", the watchdog would never
            # fire again. Walk each candidate's parent pointers.
            my_urls = {r.get("url", "").rstrip("/") for r in relays
                       if r.get("name") == my_name}
            by_url = {r.get("url", "").rstrip("/"): r for r in relays}
            for r in relays:
                cur, hops = r, 0
                while cur is not None and hops < len(relays) + 1:
                    parent = (cur.get("parent") or "").rstrip("/")
                    if parent in my_urls:
                        exclude.add(r.get("name"))
                        break
                    cur = by_url.get(parent)
                    hops += 1
        chosen = pick_relay(topo, kind=self.kinds[0],
                            exclude=tuple(n for n in exclude if n))
        if chosen is not None:
            new_url = chosen["url"]
        else:
            routers = topo.get("routers", [])
            new_url = routers[0]["url"] if routers \
                else self._wd["topology_url"]
        if new_url.rstrip("/") == dead:
            return False          # nothing better advertised yet
        self.reparent(new_url)
        self.watchdog_reparents += 1
        return True

    def reparent(self, new_upstream_url: str) -> None:
        """Re-home this relay onto a DIFFERENT parent (a sibling relay
        or the router) discovered from the topology map, resuming from
        its per-shard cursors: the shared rv space means a sibling's
        rings speak the same coordinates, so the move costs a journal
        resume — no relist, nothing dropped downstream. The old
        connection closes FIRST (the gap is exactly what the resume
        replays); a 410 from the new parent degrades to the diffed
        relist, which keeps downstream continuity by construction."""
        old = self.client
        with self._lock:
            curs = {s: rv for s, rv in self._ring_rv.items() if s}
            since = self.last_rv if self.last_rv > 0 else None
            self.upstream_url = new_upstream_url
        try:
            old.close()
        except Exception:  # noqa: BLE001 — the old parent may be dead
            pass
        self.client = self._factory(new_upstream_url)
        self.client.watch_kinds(self._handlers, replay=True,
                                since_rv=since, cursors=curs or None)

    def close(self) -> None:
        self._wd_stop.set()
        if self._wd_thread is not None:
            self._wd_thread.join(timeout=2)
        self.client.close()
        with self._lock:
            for subs in self._subs.values():
                for s in subs:
                    s.evicted = True
                    s.event.set()
            self._subs = {k: [] for k in self.kinds}


# --------------------------------------------------------------------------
# HTTP face: hubserver's /watch wire + /call passthrough + /debug/fabric
# --------------------------------------------------------------------------


class _RelayHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubernetes-tpu-relay/1"

    def log_message(self, *args) -> None:  # quiet
        pass

    @property
    def core(self) -> RelayCore:
        return self.server.core           # type: ignore[attr-defined]

    def _json(self, status: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if headers:
            for k, v in headers.items():
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        """Write passthrough: the relay fans reads out; writes go to
        the hub. Codec headers forward verbatim — the relay is
        negotiation-transparent (both ends share its fingerprint or
        settle to JSON on their own)."""
        if self.path != "/call":
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        headers = {"Content-Type": self.headers.get(
            "Content-Type", "application/json")}
        offered = self.headers.get(binwire.WIRE_HEADER)
        if offered:
            headers[binwire.WIRE_HEADER] = offered
        req = urllib.request.Request(
            self.core.upstream_url + self.path, data=body,
            headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                payload = resp.read()
                status = resp.status
                codec_hdr = resp.headers.get(binwire.WIRE_HEADER)
                ctype = resp.headers.get("Content-Type",
                                         "application/json")
        except urllib.error.HTTPError as e:
            payload = e.read()
            status = e.code
            codec_hdr = None
            ctype = "application/json"
        except OSError:
            self._json(503, {"error": "Upstream",
                             "message": "relay upstream unreachable"})
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        if codec_hdr:
            self.send_header(binwire.WIRE_HEADER, codec_hdr)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802
        from urllib.parse import parse_qs, urlparse

        path = urlparse(self.path)
        q = parse_qs(path.query)
        if path.path in ("/healthz", "/livez"):
            # fleet health: relays answer like every fabric component,
            # 503 until the upstream reflector has synced once
            if self.core._synced.is_set():
                self._send_text(200, "ok")
            else:
                self._send_text(503, "upstream not synced")
            return
        if path.path == "/metrics":
            from kubernetes_tpu.telemetry.fleet import (
                process_identity_text,
                relay_metrics_text,
            )

            self._send_text(200, process_identity_text(
                "relay", self.server.server_address[1])
                + relay_metrics_text(self.core))
            return
        if path.path == "/debug/fabric":
            auth = self.server.debug_auth     # type: ignore[attr-defined]
            if auth is None:
                self._send_text(403, "debug endpoints disabled "
                                     "(no debug_auth configured)")
                return
            if not auth(self.headers.get("Authorization", "")):
                self._send_text(401, "unauthorized")
                return
            self._json(200, self.core.debug_state())
            return
        if path.path != "/watch":
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        params, err = parse_watch_query(q)
        if params is None:
            self._json(400, {"error": "ValueError", "message": err})
            return
        mux, use_bin = params.mux, params.use_bin
        try:
            sub = self.core.subscribe(tuple(params.kinds),
                                      since_rv=params.since_rv,
                                      replay=params.replay,
                                      cursors=params.cursors,
                                      priority=watch_priority(
                                          q.get("identity", [""])[0]))
        except TooManyRequests as e:
            # backlog pressure: new best-effort subscriptions shed with
            # an honest hint instead of degrading existing streams
            self._json(429, {"error": "TooManyRequests",
                             "message": str(e)},
                       headers={"Retry-After":
                                f"{e.retry_after:.3f}"})
            return
        except RvTooOld as e:
            # cursor fell off the relay ring: the 410 that sends the
            # client back for a relist — which the relay itself serves
            self._json(410, {"error": "RvTooOld", "message": str(e),
                             "compacted_rv": e.compacted_rv})
            return
        except ValueError as e:
            self._json(400, {"error": "ValueError", "message": str(e)})
            return
        self.send_response(200)
        self.send_header("Content-Type",
                         FRAMES_CONTENT_TYPE if use_bin
                         else "application/jsonlines")
        if use_bin:
            self.send_header(binwire.WIRE_HEADER, binwire.offer())
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        write_obj, write_event = make_stream_writers(self.wfile,
                                                     use_bin, mux)

        def write_all(ds: list[dict]) -> None:
            for d in ds:
                write_event(d["kind"], d["type"], d["rv"],
                            d["old"], d["new"], d.get("trace"),
                            d.get("sh"))

        try:
            write_all(sub.drain())        # the subscribe-time backlog
            sync = {"synced": True, "rv": sub.cursor}
            if sub.sync_shards:
                sync["shards"] = dict(sub.sync_shards)
            write_obj(sync)
            while not self.server.stopping:  # type: ignore[attr-defined]
                if sub.evicted:
                    # slow-subscriber eviction: cut the stream; the
                    # client reconnects with resume (or relists)
                    return
                if not sub.event.wait(timeout=1.0):
                    write_obj({})         # keepalive
                    continue
                sub.event.clear()
                write_all(sub.drain())
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.core.unsubscribe(sub)

    def _send_text(self, code: int, text: str) -> None:
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class RelayServer:
    """relay = RelayServer(RelayCore(hub_url)).start(); point RemoteHub
    clients (or child relays) at ``relay.address``.

    ``advertise`` opts into auto-topology: ``{"state_url": <state or
    router URL>, "name": ..., "parent": ...}`` starts a heartbeat that
    registers this relay (url, parent, kinds, live subscriber count)
    with the state shard, putting it on the served topology map that
    clients and child relays discover through (``pick_relay``) instead
    of being pointed by flag. A relay that dies simply ages out of the
    map (RELAY_TTL_S)."""

    def __init__(self, core: RelayCore, host: str = "127.0.0.1",
                 port: int = 0,
                 debug_auth: Optional[Callable[[str], bool]] = None,
                 advertise: Optional[dict] = None):
        self.core = core
        self._httpd = ThreadingHTTPServer((host, port), _RelayHandler)
        self._httpd.daemon_threads = True
        self._httpd.core = core               # type: ignore[attr-defined]
        self._httpd.debug_auth = debug_auth   # type: ignore[attr-defined]
        self._httpd.stopping = False          # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None
        self._advertise = dict(advertise) if advertise else None
        self._adv_stop = threading.Event()
        self._adv_thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def _heartbeat(self) -> None:
        from kubernetes_tpu.hubclient import RemoteHub

        adv = self._advertise
        client = RemoteHub(adv["state_url"], timeout=5.0)
        interval = adv.get("interval_s", 2.0)
        try:
            while True:
                try:
                    client.fabric_register_relay({
                        "name": adv["name"],
                        "url": self.address,
                        "parent": adv.get("parent", ""),
                        "kinds": list(self.core.kinds),
                        "subscribers":
                            self.core.subscriber_count()})
                except Exception:  # noqa: BLE001 — state shard down:
                    pass           # we age out of the map, correctly
                if self._adv_stop.wait(interval):
                    return
        finally:
            client.close()

    def start(self) -> "RelayServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="watch-relay")
        self._thread.start()
        if self._advertise:
            self._adv_thread = threading.Thread(
                target=self._heartbeat, daemon=True,
                name=f"relay-advertise-{self._advertise['name']}")
            self._adv_thread.start()
        return self

    def stop(self) -> None:
        self._adv_stop.set()
        self._httpd.stopping = True           # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._adv_thread is not None:
            self._adv_thread.join(timeout=5)
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.core.close()


# --------------------------------------------------------------------------
# auto-topology discovery
# --------------------------------------------------------------------------


def pick_relay(topology: dict, kind: str = "pods", seed: int = 0,
               exclude: tuple = ()) -> Optional[dict]:
    """Choose a relay from a served topology map: prefer LEAF relays
    (nothing re-parents onto an interior node unless it must), then
    the least-subscribed, tie-broken by a stable hash so a client
    population spreads instead of stampeding one relay. Returns the
    relay record or None (caller falls back to the router)."""
    import zlib as _z

    relays = [r for r in topology.get("relays", [])
              if kind in r.get("kinds", ["pods"])
              and r.get("name") not in exclude]
    if not relays:
        return None
    parents = {r.get("parent", "") for r in relays}
    leaves = [r for r in relays if r["url"] not in parents]
    pool = leaves or relays
    return min(pool, key=lambda r: (
        r.get("subscribers", 0),
        _z.crc32(f"{r['name']}:{seed}".encode())))


def discover_relay_url(topology_url: str, kind: str = "pods",
                       seed: int = 0, exclude: tuple = ()) -> str:
    """Fetch the topology map from a router and return the chosen
    relay's URL, falling back to the first router (or the topology URL
    itself) when no relay is advertised yet — a client is never
    stranded by an empty map."""
    from kubernetes_tpu.fabric.router import fetch_topology

    topo = fetch_topology(topology_url)
    chosen = pick_relay(topo, kind=kind, seed=seed, exclude=exclude)
    if chosen is not None:
        return chosen["url"]
    routers = topo.get("routers", [])
    return routers[0]["url"] if routers else topology_url
