"""Fan-in scale smoke: 10k kubelet-analog reflectors through a relay tree.

The ``bench.py --fanout-smoke`` gate. One hub, a chaos proxy in front of
it, two level-1 relay nodes dialing upstream through the proxy, eight
level-2 relay nodes dialing the level-1s, and 10k simulated reflectors
(in-process subscribers — bounded queues and resume cursors, the exact
relay-facing surface an HTTP reflector has, without 10k sockets of
harness overhead) hanging off the level-2s.

Gates (the ISSUE-9 acceptance criteria):

* the hub holds ≤ level-1-relay-count pod watch sockets, however many
  reflectors subscribe downstream;
* a chaos watch-cut storm against the relays' upstream streams
  reconnects via journal RESUME every time — zero relists, zero lost
  events (every subscriber converges to the hub's final revision with
  the exact event count);
* a mid-storm reconnect wave of downstream subscribers is served
  entirely from the relay rings (resume), never from the hub;
* a deliberately slow subscriber is EVICTED (bounded queue) and counted,
  then catches back up via resume after reconnecting — backpressure
  cuts one consumer, not the tree;
* the binary wire codec carries the same event stream in ≤ 1/3 the
  bytes of the JSON wire (measured on the storm's own events);
* a scheduler's drift sentinel in steady state issues ZERO full LIST
  calls (journal-rv incremental diffing, ROADMAP's carried-over
  O(cluster) gap).
"""

from __future__ import annotations

import json
import time

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.fabric.relay import RelayCore
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.utils.wire import to_wire


def _wire_bytes(events: list[dict]) -> tuple[int, int]:
    """(json_bytes, bin1_bytes) for the same event stream — the
    wire-bytes-per-cycle comparison, measured on real storm events."""
    jb = bb = 0
    for ev in events:
        jb += len(json.dumps(to_wire(ev)).encode()) + 1   # + newline
        bb += len(binwire.frame(binwire.encode(ev)))
    return jb, bb


def _drift_steady_state(nodes: int = 16, pods: int = 32) -> dict:
    """Mini drift-sentinel check: after the first (full) pass, a
    steady-state pass must issue ZERO cluster LISTs — the incremental
    comparer reads only the journal suffix."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import CountingHub, MakeNode, MakePod

    hub = Hub()
    counting = CountingHub(hub)
    for i in range(nodes):
        hub.create_node(MakeNode().name(f"dn-{i}").capacity(
            cpu="16").obj())
    sched = Scheduler(counting, default_config(),
                      caps=Capacities(nodes=max(32, nodes * 2),
                                      pods=max(128, pods * 2)))
    try:
        for i in range(pods):
            hub.create_pod(MakePod().name(f"dp-{i}").req(
                cpu="100m").obj())
        sched.run_until_idle()
        sched.drift_check_interval = 1e-9
        sched._last_drift_check = 0.0
        sched._run_drift_sentinel()             # first pass: full diff
        first_lists = counting.lists
        # steady state: nothing changed — the sentinel must not LIST
        counting.lists = 0
        sched._last_drift_check = 0.0
        sched._run_drift_sentinel()
        steady_lists = counting.lists
        # ...and a small change costs O(changes), still zero LISTs
        hub.create_pod(MakePod().name("dp-late").req(cpu="100m").obj())
        sched.run_until_idle()
        counting.lists = 0
        sched._last_drift_check = 0.0
        sched._run_drift_sentinel()
        changed_lists = counting.lists
        return {"first_pass_lists": first_lists,
                "steady_lists": steady_lists,
                "changed_lists": changed_lists,
                "incremental_passes": sched.stats["drift_incremental"],
                "ok": steady_lists == 0 and changed_lists == 0
                and first_lists > 0}
    finally:
        sched.close()
        hub.close()


def _e2e_traced_pipeline(hub, relay_url: str, server_address: str,
                         l1_servers, nodes: int = 16, pods: int = 48,
                         timeout_s: float = 90.0) -> dict:
    """The end-to-end SLO phase (ISSUE-10): a scheduler against the
    hub, hollow kubelets whose pod WATCHES ride the relay tree, and a
    per-pod joined timeline — hub commit (created) -> relay hop
    (kubelet_recv carries the hop count) -> scheduler cycle -> bind
    commit (bound) -> kubelet ack commit (acked). Gates: every pod
    binds, >= 99% of bound pods have a COMPLETE joined trace including
    the relay leg, and the run reports a created->acked p99.

    Also scrapes the fleet while every component is alive: FleetView
    over the hub server, each L1 relay, and the kubemark feeder — all
    healthy, and the merged exposition re-parses strictly."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.kubemark import HollowNodes
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.telemetry.fleet import FleetView
    from kubernetes_tpu.telemetry.trace import latency_summary
    from kubernetes_tpu.testing import MakePod

    prof_name = "e2e-sched"      # leave the storm's fan/churn pods alone
    cfg = default_config()
    cfg.profiles[0].scheduler_name = prof_name
    watch_client = RemoteHub(relay_url, timeout=10.0)
    hollow = HollowNodes(hub, nodes, prefix="e2e", cpu="32",
                         watch_hub=watch_client)
    sched = Scheduler(hub, cfg,
                      caps=Capacities(nodes=64, pods=256))
    created: list[str] = []
    try:
        for i in range(pods):
            p = MakePod().name(f"e2e-{i}").namespace("e2e") \
                .scheduler_name(prof_name).req(cpu="100m").obj()
            hub.create_pod(p)
            created.append(p.metadata.uid)

        def complete() -> int:
            return sum(1 for uid in created
                       if sched.timelines.joined(uid) is not None)

        deadline = time.monotonic() + timeout_s
        while complete() < pods and time.monotonic() < deadline:
            sched.run_until_idle()
            time.sleep(0.05)
        joins = [j for j in (sched.timelines.joined(uid)
                             for uid in created) if j is not None]
        bound = sum(1 for uid in created
                    if (hub.get_pod(uid) is not None
                        and hub.get_pod(uid).spec.node_name))
        with_relay_leg = sum(1 for j in joins
                             if "bind_to_kubelet_s" in j)
        lat = latency_summary([j["create_to_ack_s"] for j in joins])
        out = {
            "pods": pods, "bound": bound,
            "joinable": len(joins),
            "joinable_frac": round(len(joins) / max(bound, 1), 4),
            "relay_leg_frac": round(with_relay_leg / max(bound, 1), 4),
            "relay_hops_max": max((j["relay_hops"] for j in joins),
                                  default=0),
            "created_to_acked": lat,
            "ok": (bound == pods
                   and len(joins) >= 0.99 * bound
                   and with_relay_leg >= 0.99 * bound
                   and lat.get("p99_s") is not None),
        }

        # fleet aggregation, scraped while everything is alive
        feeder_ep = hollow.serve_metrics()
        endpoints = [{"component": "hub", "shard": "hub",
                      "url": server_address}]
        endpoints += [{"component": "relay", "shard": f"l1-{i}",
                       "url": s.address}
                      for i, s in enumerate(l1_servers)]
        endpoints.append({"component": "kubemark", "shard": "feeder",
                          "url": feeder_ep.address})
        fleet = FleetView(endpoints)
        records = fleet.scrape()        # ONE round of HTTP round-trips
        summary = fleet.summary(records)
        merged = fleet.render_text(records)
        from kubernetes_tpu.telemetry.fleet import parse_exposition

        merged_exp = parse_exposition(merged)   # strict: raises on rot
        labeled = all("component" in s.labels
                      for s in merged_exp.samples)
        out["fleet"] = {
            "endpoints": summary["total"],
            "healthy": summary["healthy"],
            "merged_samples": len(merged_exp.samples),
            "ok": summary["ok"] and labeled
            and len(merged_exp.samples) > 0,
        }
        return out
    finally:
        sched.close()
        hollow.stop()
        watch_client.close()


def run_fanout_smoke(subscribers: int = 10000, l1_count: int = 2,
                     l2_count: int = 8, pods: int = 120,
                     churn: int = 60, cuts: int = 10,
                     resub: int = 500, seed: int = 23,
                     timeout_s: float = 240.0) -> dict:
    """The storm. Returns the invariant report; ``ok`` is True iff
    every gate above held."""
    from kubernetes_tpu.chaos import ChaosConfig, ChaosProxy
    from kubernetes_tpu.fabric.relay import RelayServer
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.testing import MakePod

    report: dict = {"subscribers": subscribers, "l1": l1_count,
                    "l2": l2_count, "pods": pods, "cuts": cuts,
                    "seed": seed}
    hub = Hub(journal_capacity=65536)
    server = HubServer(hub).start()
    proxy = ChaosProxy(server.address,
                       config=ChaosConfig(seed=seed)).start()
    l1_servers: list[RelayServer] = []
    l2_cores: list[RelayCore] = []
    try:
        # the tree: hub <- proxy <- L1 relays <- L2 relays <- subscribers
        for _ in range(l1_count):
            core = RelayCore(proxy.address, kinds=("pods",),
                             ring_capacity=65536, timeout=10.0)
            l1_servers.append(RelayServer(core).start())
        for i in range(l2_count):
            l2_cores.append(RelayCore(
                l1_servers[i % l1_count].address, kinds=("pods",),
                ring_capacity=65536, timeout=10.0))
        subs = [l2_cores[i % l2_count].subscribe(
                    ("pods",), queue_limit=1_000_000)
                for i in range(subscribers)]
        resubbed: set[int] = set()

        # ---- phase 1: pod storm ----
        t0 = time.monotonic()
        for i in range(pods):
            hub.create_pod(MakePod().name(f"fan-{i}")
                           .namespace(f"ns-{i % 7}")
                           .req(cpu="100m").obj())

        def l1_stats(key: str) -> int:
            return sum(s.core.client.resilience_stats()[key]
                       for s in l1_servers)

        # ---- phase 2: watch-cut storm on the L1 upstream streams ----
        # every cut must heal by journal RESUME (since_rv), never by a
        # relist; churn pods keep events flowing so cuts trigger
        base_resumes = l1_stats("watch_resumes")
        base_relists = l1_stats("watch_relists")
        proxy.set_fault(watch_cut_every=3)
        ci = 0
        deadline = time.monotonic() + timeout_s / 2
        while l1_stats("watch_resumes") - base_resumes < cuts \
                and time.monotonic() < deadline:
            p = MakePod().name(f"churn-{ci}").namespace("churn") \
                .req(cpu="50m").obj()
            hub.create_pod(p)
            if ci >= 1 and ci % 2 == 0:
                # deletes too: the resume path must carry tombstones
                doomed = [x for x in hub.list_pods()
                          if x.metadata.namespace == "churn"]
                if doomed:
                    try:
                        hub.delete_pod(doomed[0].metadata.uid)
                    except Exception:  # noqa: BLE001 — already gone
                        pass
            ci += 1
            if ci > churn:
                time.sleep(0.2)
            else:
                time.sleep(0.05)
        proxy.set_fault(watch_cut_every=0)
        proxy.heal()
        report["upstream_resumes"] = l1_stats("watch_resumes") \
            - base_resumes
        report["upstream_relists"] = l1_stats("watch_relists") \
            - base_relists

        # ---- phase 3: mid-storm downstream reconnect wave ----
        # every reconnect resumes off a relay RING; the hub never sees
        # one of these
        ring_410 = 0
        for i in range(0, min(resub, subscribers)):
            idx = (i * 37) % subscribers     # deterministic spread
            if idx in resubbed:
                continue
            core = l2_cores[idx % l2_count]
            old = subs[idx]
            core.unsubscribe(old)
            try:
                subs[idx] = core.subscribe(("pods",),
                                           since_rv=old.cursor,
                                           queue_limit=1_000_000)
            except Exception:  # noqa: BLE001 — RvTooOld = ring moved
                ring_410 += 1
                subs[idx] = core.subscribe(("pods",),
                                           queue_limit=1_000_000)
            resubbed.add(idx)
        resume_serves = sum(c.resume_serves for c in l2_cores)
        report["resub_wave"] = len(resubbed)
        report["resub_ring_410s"] = ring_410
        report["relay_resume_serves"] = resume_serves

        # ---- phase 4: convergence ----
        pod_events = [c for c in hub.list_changes(0, ("pods",))
                      .get("changes", [])]
        target_rv = max((c["rv"] for c in pod_events), default=0)
        expected = len(pod_events)
        deadline = time.monotonic() + timeout_s / 2
        lagging = subscribers
        while time.monotonic() < deadline:
            lagging = sum(1 for s in subs
                          if s.cursor < target_rv and not s.evicted)
            if lagging == 0:
                break
            time.sleep(0.25)
        report["lagging_subscribers"] = lagging
        report["target_rv"] = target_rv
        report["pod_events"] = expected
        # exact-count check on the never-reconnected subscribers: a
        # relay tree that drops or duplicates would show here
        drained = [s.drain() for i, s in enumerate(subs)
                   if i not in resubbed]
        counts = [len(evs) for evs in drained]
        report["event_count_min"] = min(counts)
        report["event_count_max"] = max(counts)
        exact = min(counts) == max(counts) == expected
        # trace propagation: every live event reaching an L2 subscriber
        # crossed exactly two relay hops, stamp intact (chaos proxy on
        # the upstream leg strips the CODEC, never the in-body trace)
        total_evs = traced = 0
        for evs in drained:
            for d in evs:
                total_evs += 1
                tr = d.get("trace")
                if tr is not None and tr.hops == 2 \
                        and tr.origin == "hub" and tr.ts > 0:
                    traced += 1
        report["events_traced_frac"] = round(
            traced / max(total_evs, 1), 4)
        report["fanout_elapsed_s"] = round(time.monotonic() - t0, 2)

        # ---- phase 5: slow-subscriber eviction ----
        evictions_before = sum(c.slow_evictions for c in l2_cores)
        slow = l2_cores[0].subscribe(("pods",), queue_limit=4)
        for i in range(8):
            hub.create_pod(MakePod().name(f"evict-{i}")
                           .namespace("evict").req(cpu="50m").obj())
        deadline = time.monotonic() + 20.0
        while not slow.evicted and time.monotonic() < deadline:
            time.sleep(0.1)
        report["slow_evicted"] = slow.evicted
        report["slow_evictions_total"] = \
            sum(c.slow_evictions for c in l2_cores) - evictions_before
        # the evicted consumer reconnects and resumes where it stood
        recovered = l2_cores[0].subscribe(("pods",),
                                          since_rv=slow.cursor,
                                          queue_limit=1_000_000)
        final_rv = hub.current_rv
        deadline = time.monotonic() + 20.0
        while recovered.cursor < final_rv \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        report["evicted_recovered"] = recovered.cursor >= final_rv

        # ---- phase 6: upstream socket accounting ----
        # the hub's pod store must hold ≤ one watch registration per L1
        # relay (cut streams unregister within a keepalive)
        deadline = time.monotonic() + 15.0
        while len(hub._pods.handlers) > l1_count \
                and time.monotonic() < deadline:
            time.sleep(0.5)
        report["hub_pod_watchers"] = len(hub._pods.handlers)

        # ---- phase 7: wire bytes, same storm both codecs ----
        wire_events = [{"type": c["type"], "rv": c["rv"],
                        "old": None if c["type"] != "delete"
                        else c["obj"],
                        "new": None if c["type"] == "delete"
                        else c["obj"]}
                       for c in pod_events]
        jb, bb = _wire_bytes(wire_events)
        report["wire_bytes_json"] = jb
        report["wire_bytes_bin1"] = bb
        report["wire_ratio"] = round(jb / max(bb, 1), 2)

        # ---- phase 8: drift sentinel steady state ----
        report["drift"] = _drift_steady_state()

        # ---- phase 9: e2e joined-trace SLO + fleet aggregation ----
        # scheduler + hollow kubelets (watching through the relay tree)
        # over the SAME storm-worn fabric: >= 99% of bound pods must
        # join a complete created -> bound -> acked trace with the
        # relay leg measured, and every component's /metrics + /healthz
        # must merge into one healthy fleet exposition
        report["e2e"] = _e2e_traced_pipeline(
            hub, l1_servers[0].address, server.address, l1_servers)

        report["ok"] = bool(
            report["upstream_resumes"] >= cuts
            and report["upstream_relists"] == 0
            and lagging == 0
            and exact
            and report["events_traced_frac"] >= 0.99
            and report["resub_ring_410s"] == 0
            and report["relay_resume_serves"] >= len(resubbed)
            and report["slow_evicted"]
            and report["slow_evictions_total"] >= 1
            and report["evicted_recovered"]
            and report["hub_pod_watchers"] <= l1_count
            and report["wire_ratio"] >= 3.0
            and report["drift"]["ok"]
            and report["e2e"]["ok"]
            and report["e2e"]["fleet"]["ok"])
    finally:
        for c in l2_cores:
            c.close()
        for s in l1_servers:
            s.stop()
        proxy.stop()
        server.stop()
        hub.close()
    return report


def _wal_bytes(events: list[dict]) -> tuple[int, int]:
    """(json_bytes, bin1_bytes) for the same WAL record stream — the
    replay-size ratio the bin1 journal WAL buys, measured on the
    storm's own events (the satellite's bench-artifact number)."""
    from kubernetes_tpu.storage import Journal, JournalEvent

    jb = bb = 0
    for ev in events:
        rec = Journal._event_record(JournalEvent(
            rv=ev["rv"], kind="pods", type=ev["type"],
            old=ev.get("old"), new=ev.get("new")))
        jb += len(Journal._json_record(rec).encode()) + 1
        bb += len(binwire.frame(binwire.encode(rec)))
    return jb, bb


def run_fanout_smoke_procs(subscribers: int = 50000, l1_count: int = 2,
                           l2_count: int = 4, pods: int = 80,
                           churn: int = 40, cuts: int = 10,
                           resub: int = 300, seed: int = 23,
                           pod_shards: int = 2,
                           timeout_s: float = 360.0) -> dict:
    """The PROCESS-MODE storm (ISSUE 11): shards as separate OS
    processes behind the stateless router, relays discovered through
    the served topology map (no flags), hollow-kubelet-analog
    subscribers hanging off the auto-discovered tree. On top of the
    in-process smoke's gates, this one must survive

    * a watch-cut storm against the L1 relays' upstream streams
      (healed by composite-cursor RESUME — 0 relists),
    * one ``kill -9``'d pod-shard process mid-storm, restarted by the
      supervisor with bin1-WAL replay onto a new port,
    * one LIVE ring rebalance mid-storm (event-silent, resume points
      intact),
    * one ``kill -9``'d **state-core LEADER** mid-storm (the shared
      rv/fencing/ring quorum — ISSUE 13): a new leader is elected,
      commits stall briefly and resume, the killed replica rejoins
      from its WAL, and the stream invariants below still hold,

    with exact per-subscriber event counts, ≤ l1_count router sockets
    per shard process, and a FleetView scrape showing every process
    (incl. all three state replicas, exactly one of them leading)
    healthy under its own pid/port identity."""
    import tempfile

    from kubernetes_tpu.fabric.cluster import RING_SLOTS, ring_slot
    from kubernetes_tpu.fabric.relay import (
        RelayCore,
        RelayServer,
        discover_relay_url,
    )
    from kubernetes_tpu.fabric.router import fetch_topology
    from kubernetes_tpu.fabric.supervisor import spawn_local_cluster
    from kubernetes_tpu.hub import Unavailable
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.telemetry.fleet import FleetView
    from kubernetes_tpu.testing import MakePod

    # the exact-count gate needs untouched subscribers left over after
    # the reconnect wave
    resub = min(resub, subscribers // 3)
    report: dict = {"procs": True, "subscribers": subscribers,
                    "l1": l1_count, "l2": l2_count, "pods": pods,
                    "cuts": cuts, "seed": seed,
                    "pod_shards": pod_shards, "state_replicas": 3}
    wal_dir = tempfile.mkdtemp(prefix="fabric-smoke-wal-")
    cluster = spawn_local_cluster(pod_shards=pod_shards,
                                  wal_dir=wal_dir, state_replicas=3)
    client = RemoteHub(cluster.router_url, timeout=10.0)
    l1_servers: list[RelayServer] = []
    l2_cores: list[RelayCore] = []

    def create_retry(pod, deadline_s: float = 30.0) -> None:
        # the kill -9 window: writes to the dead shard's segment fail
        # Unavailable until the supervisor restart re-registers it
        end = time.monotonic() + deadline_s
        while True:
            try:
                client.create_pod(pod)
                return
            except Unavailable:
                if time.monotonic() > end:
                    raise
                time.sleep(0.2)

    try:
        # ---- the tree, discovered not configured ----
        for i in range(l1_count):
            core = RelayCore(cluster.router_url, kinds=("pods",),
                             ring_capacity=65536, timeout=10.0)
            l1_servers.append(RelayServer(
                core, advertise={"state_url": cluster.router_url,
                                 "name": f"l1-{i}",
                                 "parent": cluster.router_url,
                                 "interval_s": 0.5}).start())
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            topo = fetch_topology(cluster.router_url)
            if len(topo.get("relays", [])) >= l1_count:
                break
            time.sleep(0.2)
        report["advertised_relays"] = len(topo.get("relays", []))
        for i in range(l2_count):
            # each L2 discovers its parent from the served map
            url = discover_relay_url(cluster.router_url, seed=i)
            l2_cores.append(RelayCore(url, kinds=("pods",),
                                      ring_capacity=65536,
                                      timeout=10.0))
        subs = [l2_cores[i % l2_count].subscribe(
                    ("pods",), queue_limit=2_000_000)
                for i in range(subscribers)]
        resubbed: set[int] = set()

        def l1_stats(key: str) -> int:
            return sum(s.core.client.resilience_stats()[key]
                       for s in l1_servers)

        # ---- phase 1: pod storm across shards ----
        t0 = time.monotonic()
        for i in range(pods):
            create_retry(MakePod().name(f"fan-{i}")
                         .namespace(f"ns-{i % 7}")
                         .req(cpu="100m").obj())

        # ---- phase 2: watch-cut storm on the L1 upstream streams ----
        base_resumes = l1_stats("watch_resumes")
        base_relists = l1_stats("watch_relists")
        ci = 0
        deadline = time.monotonic() + timeout_s / 3
        while l1_stats("watch_resumes") - base_resumes < cuts \
                and time.monotonic() < deadline:
            if ci % 2 == 0:
                # cut a relay's upstream socket (no proxy in the
                # process fabric: the cut IS the failure mode)
                victim = l1_servers[ci % l1_count].core.client
                with victim._wlock:
                    handles = list(victim._watchers)
                for h in handles:
                    try:
                        h.close()
                    except OSError:
                        pass
            create_retry(MakePod().name(f"churn-{ci}")
                         .namespace("churn").req(cpu="50m").obj())
            if ci >= 1 and ci % 2 == 0:
                doomed = [x for x in client.list_pods()
                          if x.metadata.namespace == "churn"]
                if doomed:
                    try:
                        client.delete_pod(doomed[0].metadata.uid)
                    except Exception:  # noqa: BLE001 — already gone
                        pass
            ci += 1
            time.sleep(0.05 if ci <= churn else 0.2)
        report["upstream_resumes"] = l1_stats("watch_resumes") \
            - base_resumes
        report["upstream_relists"] = l1_stats("watch_relists") \
            - base_relists

        # ---- phase 3: kill -9 a shard process mid-storm ----
        victim_shard = cluster.pod_shards[0]
        ring_now = client.fabric_ring()
        live_ns = [f"ns-{i}" for i in range(7)
                   if ring_now["slots"][ring_slot(
                       f"ns-{i}", len(ring_now["slots"]))]
                   != victim_shard]
        report["killed_pid"] = cluster.sup.kill_shard(victim_shard)
        # keep committing: the live shard keeps flowing while the dead
        # one's segment waits out the restart
        for i in range(6):
            create_retry(MakePod().name(f"during-kill-{i}")
                         .namespace(live_ns[i % len(live_ns)])
                         .req(cpu="50m").obj())
        restarted = cluster.sup.restart_shard(victim_shard)
        report["restarted_port"] = restarted.port
        for i in range(6):
            create_retry(MakePod().name(f"after-kill-{i}")
                         .namespace(f"ns-{i % 7}").req(cpu="50m").obj())

        # ---- phase 4: LIVE ring rebalance mid-storm ----
        ring = client.fabric_ring()
        slot = ring_slot("ns-0", len(ring["slots"]) or RING_SLOTS)
        src = ring["slots"][slot]
        dst = next(n for n in cluster.pod_shards if n != src)
        report["rebalance"] = client.rebalance_segment([slot], dst)
        for i in range(4):
            create_retry(MakePod().name(f"post-move-{i}")
                         .namespace("ns-0").req(cpu="50m").obj())

        # ---- phase 4b: kill -9 the state-core LEADER mid-storm ----
        # rv allocation, fencing, and the ring live on the quorum: the
        # kill costs a brief write stall (redirect-retried), never a
        # relist, never a lost or duplicated event downstream
        state_leader = cluster.state_leader()
        report["state_leader_killed"] = state_leader
        report["state_leader_pid"] = cluster.sup.kill_shard(state_leader)
        for i in range(6):
            create_retry(MakePod().name(f"during-state-kill-{i}")
                         .namespace(f"ns-{i % 7}").req(cpu="50m").obj())
        report["state_new_leader"] = cluster.state_leader(timeout_s=30.0)
        restarted_state = cluster.sup.restart_shard(state_leader)
        report["state_restarted_port"] = restarted_state.port
        for i in range(4):
            create_retry(MakePod().name(f"after-state-kill-{i}")
                         .namespace(f"ns-{i % 7}").req(cpu="50m").obj())

        # ---- phase 5: mid-storm downstream reconnect wave ----
        # composite-cursor resumes off the relay rings: zero 410s even
        # across the kill and the rebalance
        ring_410 = 0
        for i in range(0, min(resub, subscribers)):
            idx = (i * 37) % subscribers
            if idx in resubbed:
                continue
            core = l2_cores[idx % l2_count]
            old = subs[idx]
            core.unsubscribe(old)
            try:
                subs[idx] = core.subscribe(
                    ("pods",), since_rv=old.cursor,
                    cursors={k: v for k, v in old.cursors.items()
                             if k},
                    queue_limit=2_000_000)
            except Exception:  # noqa: BLE001 — RvTooOld = ring moved
                ring_410 += 1
                subs[idx] = core.subscribe(("pods",),
                                           queue_limit=2_000_000)
            resubbed.add(idx)
        report["resub_wave"] = len(resubbed)
        report["resub_ring_410s"] = ring_410
        report["relay_resume_serves"] = sum(c.resume_serves
                                            for c in l2_cores)

        # ---- phase 6: convergence + exact per-subscriber counts ----
        changes = client.list_changes(0, ("pods",)).get("changes", [])
        expected = len(changes)
        stats = client.get_journal_stats()
        target_curs = {name: st.get("rv", 0)
                       for name, st in stats["shards"].items()
                       if name in cluster.pod_shards}

        def lagging_count() -> int:
            n = 0
            for s in subs:
                if s.evicted:
                    continue
                for shard, rv in target_curs.items():
                    if s.cursors.get(shard, 0) < rv:
                        n += 1
                        break
            return n

        deadline = time.monotonic() + timeout_s / 3
        lagging = subscribers
        while time.monotonic() < deadline:
            lagging = lagging_count()
            if lagging == 0:
                break
            time.sleep(0.25)
        report["lagging_subscribers"] = lagging
        report["pod_events"] = expected
        drained = [s.drain() for i, s in enumerate(subs)
                   if i not in resubbed]
        counts = [len(evs) for evs in drained]
        report["event_count_min"] = min(counts)
        report["event_count_max"] = max(counts)
        exact = min(counts) == max(counts) == expected
        shards_seen = {d.get("sh") for evs in drained[:50]
                       for d in evs}
        report["shards_seen"] = sorted(s for s in shards_seen if s)

        # ---- phase 7: slow-subscriber eviction + recovery ----
        evict_before = sum(c.slow_evictions for c in l2_cores)
        slow = l2_cores[0].subscribe(("pods",), queue_limit=4)
        for i in range(8):
            create_retry(MakePod().name(f"evict-{i}")
                         .namespace("evict").req(cpu="50m").obj())
        deadline = time.monotonic() + 20.0
        while not slow.evicted and time.monotonic() < deadline:
            time.sleep(0.1)
        report["slow_evicted"] = slow.evicted
        report["slow_evictions_total"] = \
            sum(c.slow_evictions for c in l2_cores) - evict_before
        recovered = l2_cores[0].subscribe(
            ("pods",), since_rv=slow.cursor,
            cursors={k: v for k, v in slow.cursors.items() if k},
            queue_limit=2_000_000)
        final_curs = {name: st.get("rv", 0) for name, st in
                      client.get_journal_stats()["shards"].items()
                      if name in cluster.pod_shards}
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if all(recovered.cursors.get(s, 0) >= rv
                   for s, rv in final_curs.items()):
                break
            time.sleep(0.1)
        report["evicted_recovered"] = all(
            recovered.cursors.get(s, 0) >= rv
            for s, rv in final_curs.items())

        # ---- phase 8: per-shard-process socket accounting ----
        # each shard process must hold ≤ l1_count pod watch streams —
        # the router's pass-through conns, one per L1 relay, however
        # many subscribers hang downstream
        shard_watchers = {}
        for name, rec in client.fabric_shards().items():
            if name not in cluster.pod_shards:
                continue
            sc = RemoteHub(rec["url"], timeout=5.0)
            try:
                st = sc.get_journal_stats()
                shard_watchers[name] = st.get("watchers", {}) \
                    .get("pods", 0)
            finally:
                sc.close()
        report["shard_pod_watchers"] = shard_watchers
        sockets_ok = all(v <= l1_count
                         for v in shard_watchers.values())

        # ---- phase 9: WAL replay-size ratio (bin1 vs JSON lines) ----
        wire_events = [{"rv": c["rv"], "type": c["type"],
                        "old": c["obj"] if c["type"] == "delete"
                        else None,
                        "new": None if c["type"] == "delete"
                        else c["obj"]}
                       for c in changes]
        jb, bb = _wal_bytes(wire_events)
        report["wal_bytes_json"] = jb
        report["wal_bytes_bin1"] = bb
        report["wal_replay_ratio"] = round(jb / max(bb, 1), 2)

        # ---- phase 10: fleet health with per-process identity ----
        # every state REPLICA is its own endpoint: followers answer
        # 200-with-role (healthy, not degraded) and the summary rows
        # carry who leads
        endpoints = [{"component": "state", "shard": f"state-{i}",
                      "url": u}
                     for i, u in enumerate(cluster.state_urls)]
        endpoints += [{"component": "router", "shard": "router-0",
                       "url": cluster.router_url}]
        endpoints += [{"component": "shard", "shard": name,
                       "url": rec["url"]}
                      for name, rec in
                      client.fabric_shards().items()]
        endpoints += [{"component": "relay", "shard": f"l1-{i}",
                       "url": s.address}
                      for i, s in enumerate(l1_servers)]
        fleet = FleetView(endpoints)
        records = fleet.scrape()
        summary = fleet.summary(records)
        pids = [r.get("pid") for r in summary["endpoints"]
                if r["component"] in ("state", "shard", "router")]
        state_roles = [r.get("role") for r in summary["endpoints"]
                       if r["component"] == "state"]
        report["fleet"] = {
            "endpoints": summary["total"],
            "healthy": summary["healthy"],
            "pids_distinct": len(set(pids)) == len(pids)
            and all(pids),
            "state_roles": state_roles,
            "ok": summary["ok"]
            and state_roles.count("leader") == 1,
        }
        report["fanout_elapsed_s"] = round(time.monotonic() - t0, 2)

        report["ok"] = bool(
            report["upstream_resumes"] >= cuts
            and report["upstream_relists"] == 0
            and lagging == 0
            and exact
            and report["resub_ring_410s"] == 0
            and report["relay_resume_serves"] >= len(resubbed)
            and report["slow_evicted"]
            and report["evicted_recovered"]
            and sockets_ok
            and len(report["shards_seen"]) >= 2
            and report["wal_replay_ratio"] >= 3.0
            and report["fleet"]["ok"]
            and report["fleet"]["pids_distinct"])
    finally:
        for c in l2_cores:
            c.close()
        for s in l1_servers:
            s.stop()
        client.close()
        cluster.stop()
    return report


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="relay-tree fan-out smoke (bench.py --fanout-smoke)")
    ap.add_argument("--subscribers", type=int, default=10000)
    ap.add_argument("--smoke", action="store_true",
                    help="small/fast variant (1k subscribers)")
    ap.add_argument("--procs", action="store_true",
                    help="process-mode variant: shard processes + "
                         "stateless router + auto-discovered relays "
                         "(50k subscribers unless --subscribers/"
                         "--smoke)")
    ap.add_argument("--seed", type=int, default=23)
    args = ap.parse_args()
    if args.procs:
        n = 1000 if args.smoke else (
            args.subscribers if args.subscribers != 10000 else 50000)
        r = run_fanout_smoke_procs(subscribers=n, seed=args.seed)
    else:
        n = 1000 if args.smoke else args.subscribers
        r = run_fanout_smoke(subscribers=n, seed=args.seed)
    print(json.dumps(r))
    raise SystemExit(0 if r["ok"] else 1)


if __name__ == "__main__":
    main()
