"""Flow control & overload protection: the fabric's APF analog.

The reference kube-apiserver bounds overload with API Priority &
Fairness (staging/src/k8s.io/apiserver/pkg/util/flowcontrol): every
request is classified into a priority level, each level owns a bounded
share of the server's concurrency, and requests beyond the share wait
in shuffle-sharded fair queues with bounded depth and a queue-wait
deadline — past either bound the answer is a typed 429 with a
Retry-After hint, never unbounded queue growth. This module is that
discipline for the fabric's ``/call`` wire (hub, shard, router — every
server built on hubserver's handler).

Priority levels, strictly ordered by what must survive a stampede:

* ``system``      — fabric liveness: leases, rv allocation, ring/
                    registry verbs, replica RPCs. Losing these loses
                    the control plane itself.
* ``scheduler``   — the binding path: bind, status patches, nominated-
                    node clears. Losing these stops cluster progress.
* ``tenant``      — namespaced object traffic with an extractable
                    tenant (flow id = namespace): fair-queued so one
                    noisy tenant cannot starve the rest of its level.
* ``best-effort`` — everything anonymous: unattributed reads, probes,
                    crawlers. First to shed, by design.

Each level's seat count is ``share × total_concurrency`` (strict caps:
isolation is the property the overload storm gates on, so levels never
borrow from each other). A full level fair-queues the request: the
flow id's *hand* of candidate queues is drawn by deterministic shuffle
sharding and the shortest is chosen, so a hot flow collides with a
different small subset of flows on every level reconfiguration while a
mouse flow almost always finds an empty queue. Seats released by
finishing requests hand off directly to queued waiters round-robin
across queues (fair dispatch); a waiter that outlives its level's
queue-wait deadline answers 429 like a rejected one.

The controller is transport-agnostic — ``admission()`` is a context
manager around any callable — and clock-injectable for tests.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass

from kubernetes_tpu.hub import TooManyRequests

PRIORITY_LEVELS = ("system", "scheduler", "tenant", "best-effort")

# method → level when the caller carries no identity header. Prefixes
# cover the fabric verb families (hubserver.CALL_METHODS); the
# scheduler set is the binding path any component may drive.
_SYSTEM_PREFIXES = ("leases.", "rv.", "fabric_", "replica_")
_SYSTEM_METHODS = frozenset({
    "export_segment", "import_segment", "drop_segment", "abort_export",
    "reconcile_ring", "rebalance_segment", "shard_map",
    "get_journal_stats",
})
_SCHEDULER_METHODS = frozenset({
    "bind", "patch_pod_condition", "clear_nominated_node",
    "set_pod_claim_statuses",
})
# identity prefixes → level (the X-KTPU-Identity header; RemoteHub
# stamps it from its ``identity=`` arg, the same name the telemetry
# plane uses for the component)
_SYSTEM_IDENTITIES = ("relay", "router", "shard", "state", "fabric",
                      "system", "hub")
_SCHEDULER_IDENTITIES = ("scheduler", "sched")


# watch-path backpressure (fabric.relay): the fraction of its queue
# bound a subscriber may fill while the relay is under global backlog
# pressure — best-effort cut first, the binding/system streams keep
# their full bound
PRIORITY_SHED_FACTORS = {"system": 1.0, "scheduler": 1.0,
                         "tenant": 0.5, "best-effort": 0.25}


def watch_priority(identity: str | None = None) -> str:
    """Priority level for a watch subscription, from the dial's
    ``identity=`` (same names as the /call header): fabric components
    ride system, schedulers ride scheduler, any other attributed
    consumer is a tenant, anonymous is best-effort."""
    ident = (identity or "").strip().lower()
    if ident.startswith(_SYSTEM_IDENTITIES):
        return "system"
    if ident.startswith(_SCHEDULER_IDENTITIES):
        return "scheduler"
    if ident:
        return "tenant"
    return "best-effort"


def classify_call(method: str, args=None, identity: str | None = None):
    """-> (level, flow_id). Identity outranks the verb — a scheduler's
    LIST is scheduler traffic, not best-effort — and the verb outranks
    anonymity, so an unidentified bind still rides the binding level
    (progress over protocol)."""
    ident = (identity or "").strip()
    if ident:
        low = ident.lower()
        if low.startswith(_SYSTEM_IDENTITIES):
            return "system", ident
        if low.startswith(_SCHEDULER_IDENTITIES):
            return "scheduler", ident
    if method.startswith(_SYSTEM_PREFIXES) or method in _SYSTEM_METHODS:
        return "system", ident or "system"
    if method in _SCHEDULER_METHODS:
        return "scheduler", ident or "scheduler"
    ns = _namespace_of(args)
    if ns:
        return "tenant", ns
    if ident:
        return "tenant", ident
    return "best-effort", "anon"


def _namespace_of(args) -> str | None:
    """Best-effort tenant extraction from a /call arg list: a typed
    object's metadata.namespace, or the ``ns/name`` key string the get
    verbs take. Never raises — unattributable stays unattributed."""
    if not args:
        return None
    for a in args[:2]:
        meta = getattr(a, "metadata", None)
        ns = getattr(meta, "namespace", None)
        if isinstance(ns, str) and ns:
            return ns
        if isinstance(a, str) and "/" in a:
            head = a.split("/", 1)[0]
            if head:
                return head
    return None


@dataclass
class LevelConfig:
    """One priority level's bounds. ``share`` of total concurrency
    becomes the level's seat count; ``queues`` × ``queue_depth`` bounds
    its total backlog; ``queue_wait_s`` is the deadline past which a
    queued request answers 429; ``hand_size`` is the shuffle-shard hand
    (1 = plain FIFO per level, >1 = per-flow fairness)."""

    share: float
    queues: int = 1
    queue_depth: int = 64
    queue_wait_s: float = 1.0
    hand_size: int = 1


DEFAULT_LEVELS: dict[str, LevelConfig] = {
    "system": LevelConfig(share=0.35, queues=1, queue_depth=128,
                          queue_wait_s=2.0, hand_size=1),
    "scheduler": LevelConfig(share=0.35, queues=2, queue_depth=128,
                             queue_wait_s=1.0, hand_size=1),
    "tenant": LevelConfig(share=0.22, queues=16, queue_depth=32,
                          queue_wait_s=0.5, hand_size=4),
    "best-effort": LevelConfig(share=0.08, queues=8, queue_depth=16,
                               queue_wait_s=0.25, hand_size=2),
}


class _Waiter:
    __slots__ = ("event", "granted", "qi")

    def __init__(self, qi: int):
        self.event = threading.Event()
        self.granted = False
        self.qi = qi


class _Level:
    __slots__ = ("name", "cfg", "seats", "in_flight", "queues", "rr",
                 "admitted", "queued", "rejected_full",
                 "rejected_timeout", "in_flight_peak", "depth_peak")

    def __init__(self, name: str, cfg: LevelConfig, seats: int):
        self.name = name
        self.cfg = cfg
        self.seats = seats
        self.in_flight = 0
        self.queues: list[deque] = [deque() for _ in range(cfg.queues)]
        self.rr = 0
        self.admitted = 0
        self.queued = 0
        self.rejected_full = 0
        self.rejected_timeout = 0
        self.in_flight_peak = 0
        self.depth_peak = 0

    def depth(self) -> int:
        return sum(len(q) for q in self.queues)


class FlowController:
    """``with flow.admission(method, args, identity): serve()`` —
    admits within the level's seats, fair-queues within its bounds,
    raises :class:`~kubernetes_tpu.hub.TooManyRequests` past them."""

    def __init__(self, total_concurrency: int = 64,
                 levels: dict[str, LevelConfig] | None = None,
                 clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        cfgs = dict(DEFAULT_LEVELS)
        if levels:
            cfgs.update(levels)
        self.total_concurrency = total_concurrency
        self._levels: dict[str, _Level] = {}
        for name in PRIORITY_LEVELS:
            cfg = cfgs[name]
            seats = max(1, round(cfg.share * total_concurrency))
            self._levels[name] = _Level(name, cfg, seats)
        # flow_id → hand cache (bounded): shuffle sharding is
        # deterministic per flow, no need to redraw per request
        self._hands: dict[tuple[str, str], tuple[int, ...]] = {}

    # ------------- classification -------------

    def classify(self, method: str, args=None,
                 identity: str | None = None):
        return classify_call(method, args, identity)

    # ------------- admission -------------

    @contextmanager
    def admission(self, method: str, args=None,
                  identity: str | None = None):
        level, flow_id = classify_call(method, args, identity)
        self.admit(level, flow_id, what=method)
        try:
            yield level
        finally:
            self.release(level)

    def admit(self, level_name: str, flow_id: str,
              what: str = "") -> None:
        """Take a seat at ``level_name`` or wait in ``flow_id``'s fair
        queue up to the level's queue-wait deadline. Raises
        TooManyRequests (with a Retry-After hint) on a full queue or a
        deadline breach. Every successful admit MUST be paired with
        :meth:`release`."""
        lv = self._levels[level_name]
        with self._lock:
            if lv.in_flight < lv.seats:
                lv.in_flight += 1
                lv.in_flight_peak = max(lv.in_flight_peak, lv.in_flight)
                lv.admitted += 1
                return
            qi = self._pick_queue(lv, flow_id)
            if len(lv.queues[qi]) >= lv.cfg.queue_depth:
                lv.rejected_full += 1
                raise TooManyRequests(
                    f"{level_name} level saturated "
                    f"({lv.in_flight}/{lv.seats} seats, queue full)"
                    + (f" serving {what}" if what else ""),
                    retry_after=self._retry_after(lv))
            w = _Waiter(qi)
            lv.queues[qi].append(w)
            lv.queued += 1
            lv.depth_peak = max(lv.depth_peak, lv.depth())
        if w.event.wait(lv.cfg.queue_wait_s):
            return          # a releaser handed us its seat
        with self._lock:
            if w.granted:   # grant raced the deadline: accept it
                return
            try:
                lv.queues[w.qi].remove(w)
            except ValueError:
                pass
            lv.rejected_timeout += 1
        raise TooManyRequests(
            f"{level_name} queue-wait deadline "
            f"({lv.cfg.queue_wait_s:.2f}s) breached"
            + (f" serving {what}" if what else ""),
            retry_after=self._retry_after(lv))

    def release(self, level_name: str) -> None:
        """Return a seat; if the level has queued waiters the seat
        transfers directly (round-robin across queues — the fair
        dispatch half of fair queuing)."""
        lv = self._levels[level_name]
        with self._lock:
            for i in range(len(lv.queues)):
                qi = (lv.rr + i) % len(lv.queues)
                if lv.queues[qi]:
                    w = lv.queues[qi].popleft()
                    lv.rr = (qi + 1) % len(lv.queues)
                    w.granted = True
                    lv.admitted += 1
                    w.event.set()
                    return   # seat transferred, in_flight unchanged
            lv.in_flight = max(0, lv.in_flight - 1)

    # ------------- internals -------------

    def _pick_queue(self, lv: _Level, flow_id: str) -> int:
        """Shuffle sharding: the flow's deterministic hand of candidate
        queues, shortest wins. Caller holds the lock."""
        n = len(lv.queues)
        if n == 1:
            return 0
        key = (lv.name, flow_id)
        hand = self._hands.get(key)
        if hand is None:
            rng = random.Random(zlib.crc32(
                f"{lv.name}/{flow_id}".encode()))
            hand = tuple(rng.sample(range(n),
                                    min(lv.cfg.hand_size, n)))
            if len(self._hands) >= 4096:   # bounded flow memory
                self._hands.clear()
            self._hands[key] = hand
        return min(hand, key=lambda i: len(lv.queues[i]))

    def _retry_after(self, lv: _Level) -> float:
        """Honest hint: one queue-wait window, stretched by how far
        over its backlog bound the level is. Caller holds the lock."""
        bound = max(1, len(lv.queues) * lv.cfg.queue_depth)
        return round(min(5.0, lv.cfg.queue_wait_s
                         * (1.0 + lv.depth() / bound)), 3)

    # ------------- introspection -------------

    def stats(self) -> dict:
        with self._lock:
            levels = {}
            for name, lv in self._levels.items():
                levels[name] = {
                    "seats": lv.seats,
                    "in_flight": lv.in_flight,
                    "in_flight_peak": lv.in_flight_peak,
                    "queue_depth": lv.depth(),
                    "queue_depth_bound": len(lv.queues)
                    * lv.cfg.queue_depth,
                    "depth_peak": lv.depth_peak,
                    "admitted": lv.admitted,
                    "queued": lv.queued,
                    "rejected_full": lv.rejected_full,
                    "rejected_timeout": lv.rejected_timeout,
                }
            return {"total_concurrency": self.total_concurrency,
                    "levels": levels}

    def rejected_total(self) -> int:
        with self._lock:
            return sum(lv.rejected_full + lv.rejected_timeout
                       for lv in self._levels.values())

    def debug_state(self) -> dict:
        out = self.stats()
        with self._lock:
            for name, lv in self._levels.items():
                out["levels"][name]["per_queue"] = [
                    len(q) for q in lv.queues]
                out["levels"][name]["queue_wait_s"] = lv.cfg.queue_wait_s
                out["levels"][name]["hand_size"] = lv.cfg.hand_size
        return out

    def metrics_text(self) -> str:
        """Prometheus exposition rows (``hub_flow_*``), appended to the
        serving component's /metrics by telemetry.fleet."""
        s = self.stats()
        lines = [
            "# TYPE hub_flow_in_flight gauge",
            "# TYPE hub_flow_queue_depth gauge",
            "# TYPE hub_flow_seats gauge",
            "# TYPE hub_flow_admitted_total counter",
            "# TYPE hub_flow_rejected_total counter",
        ]
        for name, lv in sorted(s["levels"].items()):
            lab = f'{{level="{name}"}}'
            lines.append(f"hub_flow_seats{lab} {lv['seats']}")
            lines.append(f"hub_flow_in_flight{lab} {lv['in_flight']}")
            lines.append(
                f"hub_flow_queue_depth{lab} {lv['queue_depth']}")
            lines.append(
                f"hub_flow_admitted_total{lab} {lv['admitted']}")
            for reason in ("full", "timeout"):
                lines.append(
                    f'hub_flow_rejected_total{{level="{name}",'
                    f'reason="{reason}"}} '
                    f"{lv['rejected_' + reason]}")
        return "\n".join(lines) + "\n"
