"""Fabric process entrypoint: ``python -m kubernetes_tpu.fabric.proc``.

One binary, three roles — how every fabric process starts, whether the
local supervisor (fabric.supervisor) spawned it or an operator did on
another host:

* ``--role state`` — the shared-state shard (rv allocator, lease
  store, ring map, registries) behind a stock ``HubServer``;
* ``--role shard --name pods-0 --kinds pods --state URL`` — one hub
  shard process: a :class:`~kubernetes_tpu.fabric.cluster.ProcShardHub`
  with its own WAL (bin1 by default) and port, registered with the
  state shard so routers resolve it (and re-resolve it after a
  restart lands on a new port);
* ``--role router --state URL`` — a stateless router
  (fabric.router.main is equivalent; this keeps one spawn surface).

Every role prints ``LISTENING <port>`` on stdout once bound — the
supervisor (or an operator's script) reads it instead of guessing
ports — and keeps a registration heartbeat so the topology map stays
truthful.

None of this imports JAX: a shard process is a pure-Python storage
node and starts in well under a second.
"""

from __future__ import annotations

import os
import sys
import time


def _serve_state(args) -> None:
    from kubernetes_tpu.fabric.cluster import StateCore
    from kubernetes_tpu.hubserver import HubServer

    pod_shards = [s for s in (args.pod_shards or "").split(",") if s]
    core = StateCore(pod_shards=pod_shards,
                     ring_slots=args.ring_slots)
    server = HubServer(core, host=args.host, port=args.port).start()
    print(f"LISTENING {server._httpd.server_address[1]}", flush=True)
    while True:
        time.sleep(3600)


def _serve_shard(args) -> None:
    from kubernetes_tpu.fabric.cluster import ProcShardHub
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer

    state = RemoteHub(args.state, timeout=10.0)
    hub = ProcShardHub(args.name, state,
                       journal_capacity=args.journal_capacity,
                       wal_path=args.wal or None,
                       wal_codec=args.wal_codec)
    server = HubServer(hub, host=args.host, port=args.port).start()
    url = f"http://{args.host}:{server._httpd.server_address[1]}"
    kinds = [k for k in args.kinds.split(",") if k]
    reg = state.fabric_register_shard(args.name, url, kinds,
                                      os.getpid())
    if "pods" in kinds:
        # killed-mid-rebalance healing: the WAL replay may have
        # resurrected a segment this shard already handed off — drop
        # anything the authoritative ring assigns elsewhere
        ring = reg.get("ring") or state.fabric_ring()
        slots = ring.get("slots") or []
        if slots:
            owned = [i for i, n in enumerate(slots) if n == args.name]
            dropped = hub.reconcile_ring(owned, len(slots))
            if dropped:
                print(f"reconciled ring: dropped {dropped} stray pods",
                      file=sys.stderr, flush=True)
    print(f"LISTENING {server._httpd.server_address[1]}", flush=True)
    while True:
        time.sleep(args.heartbeat_s)
        try:
            state.fabric_register_shard(args.name, url, kinds,
                                        os.getpid())
        except Exception:  # noqa: BLE001 — state shard restarting
            pass


def _serve_router(args) -> None:
    from kubernetes_tpu.fabric.router import RouterServer

    server = RouterServer(args.state, host=args.host, port=args.port,
                          name=args.name).start()
    print(f"LISTENING {server.port}", flush=True)
    while True:
        time.sleep(3600)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="kubernetes_tpu.fabric.proc",
        description="fabric process entrypoint (state shard / hub "
                    "shard / router)")
    ap.add_argument("--role", required=True,
                    choices=("state", "shard", "router"))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--name", default="shard")
    ap.add_argument("--state", default=None,
                    help="state-shard URL (shard/router roles)")
    ap.add_argument("--kinds", default="",
                    help="comma list of watch kinds this shard owns; "
                         "'*' = the catch-all meta shard")
    ap.add_argument("--wal", default=None,
                    help="this shard's WAL file")
    ap.add_argument("--wal-codec", default="bin1",
                    choices=("json", "bin1"))
    ap.add_argument("--journal-capacity", type=int, default=16384)
    ap.add_argument("--pod-shards", default="",
                    help="state role: comma list of pod shard names "
                         "seeding the ring")
    ap.add_argument("--ring-slots", type=int, default=64)
    ap.add_argument("--heartbeat-s", type=float, default=2.0)
    args = ap.parse_args(argv)
    if args.role != "state" and not args.state:
        ap.error(f"--role {args.role} requires --state")
    try:
        if args.role == "state":
            _serve_state(args)
        elif args.role == "shard":
            _serve_shard(args)
        else:
            _serve_router(args)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
