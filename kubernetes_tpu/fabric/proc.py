"""Fabric process entrypoint: ``python -m kubernetes_tpu.fabric.proc``.

One binary, three roles — how every fabric process starts, whether the
local supervisor (fabric.supervisor) spawned it or an operator did on
another host:

* ``--role state`` — the shared-state shard (rv allocator, lease
  store, ring map, registries) behind a stock ``HubServer``;
* ``--role shard --name pods-0 --kinds pods --state URL`` — one hub
  shard process: a :class:`~kubernetes_tpu.fabric.cluster.ProcShardHub`
  with its own WAL (bin1 by default) and port, registered with the
  state shard so routers resolve it (and re-resolve it after a
  restart lands on a new port);
* ``--role router --state URL`` — a stateless router
  (fabric.router.main is equivalent; this keeps one spawn surface).

Every role prints ``LISTENING <port>`` on stdout once bound — the
supervisor (or an operator's script) reads it instead of guessing
ports — and keeps a registration heartbeat so the topology map stays
truthful.

None of this imports JAX: a shard process is a pure-Python storage
node and starts in well under a second.
"""

from __future__ import annotations

import os
import sys
import time


def _serve_state(args) -> None:
    from kubernetes_tpu.hubserver import HubServer

    pod_shards = [s for s in (args.pod_shards or "").split(",") if s]
    if args.peers:
        # replicated state core: one member of the quorum, peers pinned
        # by name=url (ports pre-assigned by the supervisor / operator,
        # the etcd static-bootstrap model — a replica restarts onto the
        # SAME port so its peers need no re-resolution)
        from kubernetes_tpu.fabric.replica import StateReplica

        peers = dict(p.split("=", 1) for p in args.peers.split(",") if p)
        core = StateReplica(
            args.replica_id or args.name, peers=peers,
            pod_shards=pod_shards, ring_slots=args.ring_slots,
            wal_path=args.wal or None,
            heartbeat_s=args.replica_heartbeat_s,
            election_timeout_s=(args.replica_election_s,
                                args.replica_election_s * 2))
        server = HubServer(core, host=args.host, port=args.port).start()
        core.start()
    else:
        from kubernetes_tpu.fabric.cluster import StateCore

        core = StateCore(pod_shards=pod_shards,
                         ring_slots=args.ring_slots)
        server = HubServer(core, host=args.host, port=args.port).start()
    print(f"LISTENING {server._httpd.server_address[1]}", flush=True)
    while True:
        time.sleep(3600)


def _serve_shard(args) -> None:
    from kubernetes_tpu.fabric.cluster import ProcShardHub
    from kubernetes_tpu.fabric.replica import make_state_client
    from kubernetes_tpu.hubserver import HubServer

    # a comma-separated --state is the replica set: the client resolves
    # the leader and rides out elections, so a state-leader kill -9
    # costs this shard a redirect, not a crash
    state = make_state_client(args.state, timeout=10.0,
                              redirect_deadline_s=15.0)
    hub = ProcShardHub(args.name, state,
                       journal_capacity=args.journal_capacity,
                       wal_path=args.wal or None,
                       wal_codec=args.wal_codec)
    server = HubServer(hub, host=args.host, port=args.port).start()
    url = f"http://{args.host}:{server._httpd.server_address[1]}"
    kinds = [k for k in args.kinds.split(",") if k]
    reg = state.fabric_register_shard(args.name, url, kinds,
                                      os.getpid())
    if "pods" in kinds:
        # killed-mid-rebalance healing: the WAL replay may have
        # resurrected a segment this shard already handed off — drop
        # anything the authoritative ring assigns elsewhere
        ring = reg.get("ring") or state.fabric_ring()
        slots = ring.get("slots") or []
        if slots:
            owned = [i for i, n in enumerate(slots) if n == args.name]
            dropped = hub.reconcile_ring(owned, len(slots))
            if dropped:
                print(f"reconciled ring: dropped {dropped} stray pods",
                      file=sys.stderr, flush=True)
    print(f"LISTENING {server._httpd.server_address[1]}", flush=True)
    while True:
        time.sleep(args.heartbeat_s)
        try:
            reg = state.fabric_register_shard(args.name, url, kinds,
                                              os.getpid())
            if "pods" in kinds:
                # refresh the slot fence from the authoritative ring:
                # a slot the ring assigns elsewhere answers StaleRing
                # here instead of absorbing a misrouted commit
                slots = (reg.get("ring") or {}).get("slots") or []
                if slots:
                    hub.set_ring_view(
                        [i for i, n in enumerate(slots)
                         if n == args.name], len(slots))
        except Exception:  # noqa: BLE001 — state shard restarting
            pass


def _serve_router(args) -> None:
    from kubernetes_tpu.fabric.router import RouterServer

    server = RouterServer(args.state, host=args.host, port=args.port,
                          name=args.name).start()
    print(f"LISTENING {server.port}", flush=True)
    while True:
        time.sleep(3600)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="kubernetes_tpu.fabric.proc",
        description="fabric process entrypoint (state shard / hub "
                    "shard / router)")
    ap.add_argument("--role", required=True,
                    choices=("state", "shard", "router"))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--name", default="shard")
    ap.add_argument("--state", default=None,
                    help="state-shard URL (shard/router roles)")
    ap.add_argument("--kinds", default="",
                    help="comma list of watch kinds this shard owns; "
                         "'*' = the catch-all meta shard")
    ap.add_argument("--wal", default=None,
                    help="this shard's WAL file")
    ap.add_argument("--wal-codec", default="bin1",
                    choices=("json", "bin1"))
    ap.add_argument("--journal-capacity", type=int, default=16384)
    ap.add_argument("--pod-shards", default="",
                    help="state role: comma list of pod shard names "
                         "seeding the ring")
    ap.add_argument("--ring-slots", type=int, default=64)
    ap.add_argument("--heartbeat-s", type=float, default=2.0)
    ap.add_argument("--peers", default="",
                    help="state role: comma list of name=url replica "
                         "peers (self included) — presence selects the "
                         "REPLICATED state core; --wal names this "
                         "replica's log WAL")
    ap.add_argument("--replica-id", default="",
                    help="state role: this replica's name in --peers")
    ap.add_argument("--replica-heartbeat-s", type=float, default=0.2)
    ap.add_argument("--replica-election-s", type=float, default=0.8,
                    help="minimum election timeout (max is 2x)")
    args = ap.parse_args(argv)
    if args.role != "state" and not args.state:
        ap.error(f"--role {args.role} requires --state")
    try:
        if args.role == "state":
            _serve_state(args)
        elif args.role == "shard":
            _serve_shard(args)
        else:
            _serve_router(args)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
