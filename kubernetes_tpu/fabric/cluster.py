"""Out-of-process control-plane fabric: shard processes behind a
stateless router.

PR 9's ShardedHub proved the shard/wire layers; every shard still lived
in ONE Python process. This module takes the split the rest of the way
(ROADMAP item 3 — control-plane capacity that scales with hosts):

* **shard processes** — each hub shard (nodes / events / meta /
  ``pods-<i>``) runs as its own OS process: its own ``Hub`` with its
  own lock, journal rings, WAL file (bin1 by default), and HTTP port
  (:class:`ProcShardHub`, served by the ordinary ``HubServer``);
* **the shared-state shard** — one tiny process
  (:class:`StateCore`, also served by ``HubServer``) owns exactly the
  state that cannot be split: the global **rv allocator** (every commit
  on every shard draws its revision here, so resume points and sync
  markers stay comparable across the whole fabric), the **LeaseStore**
  (fencing epochs are a property of the control plane — a deposed
  epoch is stale on every shard at once), the **crc32 ring map**
  (slot → pod-shard, CAS'd by epoch for rebalances), and the
  component **registries** (shards, routers, relays — the served
  topology map relays and clients auto-discover through);
* **the stateless router** (fabric.router) — any number of identical
  processes fronting the shard set with the single-hub wire: ``/call``
  routed by method + namespace-crc32 ring, ``/watch`` passed through
  per shard with source-shard tags so clients keep per-shard resume
  cursors.

:class:`ClusterClient` is the routing brain (used by the router
process, and directly by tests): a ``Hub``-shaped facade over one
``RemoteHub`` per shard plus the state shard.

Why per-shard cursors: each shard's stream is rv-ordered, but the
cross-shard interleave is not — shard A can commit rv 100 *after*
shard B commits rv 101, so a client that resumes "everything after my
max rv 101" would silently lose A's 100 forever. A composite cursor
(``cursors=pods-0:95,pods-1:101``) resumes every shard at exactly what
the client saw *from it*; the shared allocator makes the per-shard
suffixes add up to the complete global suffix. That is what makes
"zero relists across a shard kill or a live ring rebalance" provable
rather than probabilistic.

Rebalancing a ring segment (:meth:`ClusterClient.rebalance_segment`)
moves the segment's pods between live shard processes with **no
events**: copy to the target (WAL attach record), flip the ring (CAS
on the state shard), drop from the source (WAL detach record) — all
under the router's migrate lock so writes to the moving segment wait a
few milliseconds instead of landing on a stale owner. Watchers never
see the move; their resume points stay servable because the source
shard's journal keeps the pre-move history and new commits land on the
target with fresh (higher) revisions from the shared allocator.
"""

from __future__ import annotations

import threading
import time

from kubernetes_tpu.hub import (
    Conflict,
    Hub,
    NotFound,
    StaleRing,
    Unavailable,
)
from kubernetes_tpu.leaderelection import (
    RING_SLOTS,
    LeaseStore,
    SliceBoard,
    ring_slot,
)

RELAY_TTL_S = 10.0               # a relay missing heartbeats this long
#                                  drops out of the served topology

# single-kind hub methods, routed whole to the owning shard (mirrors
# fabric.sharded's tables — the in-process and out-of-process routers
# must agree on the split)
_NODE_METHODS = frozenset({"create_node", "update_node", "delete_node",
                           "get_node", "list_nodes"})
_EVENT_METHODS = frozenset({"record_event", "list_events"})
_POD_OBJ_METHODS = frozenset({"create_pod", "update_pod", "bind",
                              "patch_pod_condition"})
_POD_UID_METHODS = frozenset({"delete_pod", "get_pod",
                              "set_pod_claim_statuses",
                              "clear_nominated_node"})
# per-shard segment verbs: meaningful only against ONE shard process —
# the router rejects them (rebalance_segment is its move surface)
_SHARD_ONLY_METHODS = frozenset({"export_segment", "import_segment",
                                 "drop_segment", "abort_export",
                                 "reconcile_ring"})


# ring_slot / RING_SLOTS live in leaderelection (the bottom of the
# import graph) since the scheduler slice ring became the crc32 ring's
# second consumer; re-exported here so fabric code keeps one import path.

# --------------------------------------------------------------------------
# the shared-state shard
# --------------------------------------------------------------------------


class _SharedRv:
    """The global revision allocator, served over the wire as the
    ``rv.*`` verbs. Monotonic across every shard process: one counter,
    one lock, three tiny methods."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last = 0

    def next(self) -> int:
        with self._lock:
            self._last += 1
            return self._last

    def advance_to(self, rv: int) -> int:
        """Raise the floor (shard WAL replays resume past the newest
        revision any shard persisted); returns the current value."""
        with self._lock:
            if rv > self._last:
                self._last = rv
            return self._last

    def last(self) -> int:
        with self._lock:
            return self._last


class StateCore:
    """The fabric's only stateful singleton beyond the shards
    themselves: rv allocation, lease fencing, the ring map, and the
    component registries. Deliberately tiny — it serves a handful of
    sub-millisecond verbs and holds no object data, so it is never the
    scale bottleneck the split exists to remove.

    Served by the ordinary ``HubServer`` (codec negotiation, typed
    errors, retries all come for free); it only implements the verbs it
    owns, and answers ``get_journal_stats`` minimally so /metrics and
    FleetView health checks work against it."""

    def __init__(self, pod_shards: list[str] | None = None,
                 ring_slots: int = RING_SLOTS) -> None:
        self._lock = threading.Lock()
        self.rv = _SharedRv()
        self.leases = LeaseStore()
        # scheduler replicas ride the same registry/ring discipline as
        # shards: heartbeats + TTL, slice map CAS'd by epoch
        self.slices = SliceBoard(ring_slots=ring_slots)
        self._shards: dict[str, dict] = {}
        self._routers: dict[str, dict] = {}
        self._relays: dict[str, dict] = {}
        names = list(pod_shards or [])
        self._ring = {"epoch": 1,
                      "slots": [names[i % len(names)]
                                for i in range(ring_slots)]} \
            if names else {"epoch": 0, "slots": []}

    # ------------- registries -------------

    def fabric_register_shard(self, name: str, url: str,
                              kinds: list | None = None,
                              pid: int | None = None) -> dict:
        """A shard process announces itself (startup + heartbeat): the
        routers resolve shard URLs here, which is how a shard restarted
        on a NEW port heals the fabric without reconfiguration."""
        with self._lock:
            self._shards[name] = {"name": name, "url": url,
                                  "kinds": list(kinds or []),
                                  "pid": pid, "ts": time.time()}
            return {"ring": dict(self._ring)}

    def fabric_register_router(self, name: str, url: str,
                               pid: int | None = None) -> dict:
        with self._lock:
            self._routers[name] = {"name": name, "url": url,
                                   "pid": pid, "ts": time.time()}
            return {"ok": True}

    def fabric_register_relay(self, info: dict) -> dict:
        """Relay heartbeat: name, url, parent, kinds, subscribers. The
        served topology map is built from these — clients discover and
        re-parent instead of being pointed by flag."""
        with self._lock:
            rec = dict(info)
            rec["ts"] = time.time()
            self._relays[rec["name"]] = rec
            return {"ok": True}

    def fabric_shards(self) -> dict:
        with self._lock:
            return {n: dict(s) for n, s in self._shards.items()}

    def fabric_topology(self) -> dict:
        """The auto-topology surface: live relays (heartbeat within
        RELAY_TTL_S), routers, shards, and the ring epoch. Served open
        (no token): it is pure wiring, and clients need it before they
        have anything else."""
        now = time.time()
        with self._lock:
            relays = [dict(r) for r in self._relays.values()
                      if now - r["ts"] <= RELAY_TTL_S]
            return {"routers": [dict(r) for r in
                                self._routers.values()],
                    "relays": relays,
                    "shards": {n: dict(s)
                               for n, s in self._shards.items()},
                    "schedulers": self.slices.live(),
                    "ring_epoch": self._ring["epoch"],
                    "sched_ring_epoch": self.slices.ring()["epoch"]}

    # ------------- ring map -------------

    def fabric_ring(self) -> dict:
        with self._lock:
            return {"epoch": self._ring["epoch"],
                    "slots": list(self._ring["slots"])}

    def fabric_set_ring(self, ring: dict, expect_epoch: int) -> bool:
        """CAS by epoch: two routers racing a rebalance cannot both
        win — the loser re-reads and retries (or gives up)."""
        with self._lock:
            if self._ring["epoch"] != expect_epoch:
                return False
            self._ring = {"epoch": int(ring["epoch"]),
                          "slots": list(ring["slots"])}
            return True

    # ------------- scheduler slice ring (the ring's second consumer) ----

    def fabric_register_scheduler(self, name: str, url: str = "",
                                  pid: int | None = None) -> dict:
        return self.slices.register(name, url, pid)

    def fabric_unregister_scheduler(self, name: str) -> dict:
        return self.slices.unregister(name)

    def fabric_schedulers(self) -> dict:
        return self.slices.schedulers()

    def fabric_sched_ring(self) -> dict:
        return self.slices.ring()

    def fabric_set_sched_ring(self, ring: dict, expect_epoch: int) -> bool:
        return self.slices.set_ring(ring, expect_epoch)

    # ------------- fleet surface -------------

    def get_journal_stats(self) -> dict:
        """Minimal stats so /metrics renders against the state shard."""
        with self._lock:
            return {"rv": self.rv.last(), "capacity": 0, "wal": False,
                    "kinds": {},
                    "shards": {n: {"kinds": s["kinds"], "depth": 0,
                                   "compacted_rv": 0, "commits": 0,
                                   "rv": 0}
                               for n, s in self._shards.items()}}

    def close(self) -> None:
        pass


# --------------------------------------------------------------------------
# the shard process's hub
# --------------------------------------------------------------------------


class ProcShardHub(Hub):
    """One shard process's hub: a full ``Hub`` whose revisions, fencing
    epochs, and lease surface live on the shared-state shard, reached
    over the wire. Everything else — stores, journal rings, the WAL —
    is process-local, which is the point: commits on different shards
    contend only on the state shard's one-line allocator, never on each
    other's locks or WAL fsyncs.

    ``state`` is a RemoteHub (or anything with ``rv``/``leases``
    namespaces). ``rv.next`` is retry-safe: a retried draw burns a
    revision, and per-kind rv gaps are already the journal's
    contract."""

    def __init__(self, name: str, state, journal_capacity: int = 16384,
                 wal_path: str | None = None, wal_codec: str = "bin1"):
        self.shard_name = name
        self.origin = name       # trace stamps name the committing shard
        self._state = state
        self.commits = 0
        super().__init__(journal_capacity=journal_capacity,
                         wal_path=wal_path, wal_codec=wal_codec)
        # WAL replay ran with original revisions; the shared space must
        # resume past the newest this shard persisted
        if self._last_rv:
            state.rv.advance_to(self._last_rv)
        # fencing + leases are hub-wide: serve them from the state shard
        # (an elector talking to any shard reaches the same store)
        self.leases = state.leases

    def _next_rv(self) -> int:
        rv = self._state.rv.next()
        self._last_rv = rv
        return rv

    def _newest_rv(self) -> int:
        # resume checks and sync markers speak the GLOBAL space: a
        # client's since_rv may have been minted by another shard
        return self._state.rv.last()

    def _check_fence(self, verb: str, epoch, lease_name: str) -> None:
        if epoch is None:
            return
        cur = self._state.leases.epoch_of(lease_name)
        if epoch < cur:
            from kubernetes_tpu.hub import Fenced

            raise Fenced(f"{verb} from deposed epoch {epoch} "
                         f"(current {cur}, lease {lease_name!r})")

    def _commit(self, store, etype, old, new):
        self.commits += 1
        return super()._commit(store, etype, old, new)

    def get_journal_stats(self) -> dict:
        st = super().get_journal_stats()
        st["commits"] = self.commits
        st["shard"] = self.shard_name
        return st


# --------------------------------------------------------------------------
# the routing brain (lives inside each router process)
# --------------------------------------------------------------------------


class ClusterClient:
    """``Hub``-shaped facade over the shard processes: one RemoteHub
    per shard plus the state shard, routed exactly like the in-process
    ShardedHub (by kind; namespace-crc32 ring for pods; uid ops by
    probe). Stateless beyond connection handles and a TTL'd ring
    cache — run as many of these (routers) as you like.

    A shard restarting on a new port surfaces as ``Unavailable``; the
    facade re-resolves the shard's URL from the state registry and
    retries once, so a ``kill -9`` + supervisor restart heals without
    touching the callers."""

    def __init__(self, state_url: str, timeout: float = 30.0,
                 client_factory=None, ring_ttl_s: float = 3.0):
        from kubernetes_tpu.fabric.replica import make_state_client
        from kubernetes_tpu.hubclient import RemoteHub

        self._factory = client_factory or (
            lambda url: RemoteHub(url, timeout=timeout))
        # a comma-separated state URL is a REPLICA SET: the client
        # resolves the leader, follows NotLeader redirects, and rides
        # out elections — single URLs keep the classic one-StateCore
        # path byte-for-byte
        self.state = make_state_client(state_url, timeout=timeout,
                                       client_factory=client_factory)
        self.leases = self.state.leases
        self.rv = self.state.rv
        self._lock = threading.RLock()
        self._clients: dict[str, object] = {}
        self._registry: dict[str, dict] = {}
        self._ring: dict | None = None
        self._ring_ts = 0.0
        self._ring_ttl = ring_ttl_s
        # held for the duration of a rebalance; pod WRITE routing takes
        # it briefly so a write can never land on a stale segment owner
        self._migrate_lock = threading.RLock()
        # writes redirected by shard-side ring fencing (StaleRing →
        # re-resolve → retry): the multi-router coordination counter
        self.stale_ring_retries = 0
        self.refresh_shards()

    # ------------- shard resolution -------------

    def refresh_shards(self) -> None:
        reg = self.state.fabric_shards()
        with self._lock:
            for name, rec in reg.items():
                old = self._registry.get(name)
                if old is not None and old["url"] != rec["url"]:
                    # restarted on a new port: retire the stale client
                    stale = self._clients.pop(name, None)
                    if stale is not None:
                        try:
                            stale.close()
                        except Exception:  # noqa: BLE001 — teardown
                            pass
                self._registry[name] = rec

    def shard_url(self, name: str) -> str:
        with self._lock:
            rec = self._registry.get(name)
        if rec is None:
            self.refresh_shards()
            with self._lock:
                rec = self._registry.get(name)
        if rec is None:
            raise NotFound(f"unknown shard {name!r}")
        return rec["url"]

    def _client(self, name: str):
        with self._lock:
            c = self._clients.get(name)
            if c is None:
                c = self._clients[name] = self._factory(
                    self.shard_url(name))
            return c

    def _invoke(self, name: str, method: str, *args):
        try:
            return getattr(self._client(name), method)(*args)
        except Unavailable:
            # maybe the shard restarted on a new port: re-resolve once
            old = self.shard_url(name)
            self.refresh_shards()
            if self.shard_url(name) == old:
                raise
            return getattr(self._client(name), method)(*args)

    # ------------- ring / kind routing -------------

    def ring(self, fresh: bool = False) -> dict:
        now = time.monotonic()
        with self._lock:
            if not fresh and self._ring is not None \
                    and now - self._ring_ts < self._ring_ttl:
                return self._ring
        r = self.state.fabric_ring()
        with self._lock:
            self._ring, self._ring_ts = r, now
            return r

    def pod_shard_names(self) -> list[str]:
        seen: list[str] = []
        for name in self.ring()["slots"]:
            if name not in seen:
                seen.append(name)
        return seen

    def _pod_shard_name(self, namespace: str) -> str:
        slots = self.ring()["slots"]
        if not slots:
            raise Unavailable("fabric ring is empty (no pod shards)")
        return slots[ring_slot(namespace, len(slots))]

    def _kind_owner(self, watch_kind: str) -> str:
        """Owning shard for a non-pod watch kind: exact kinds match in
        the registry, else the catch-all ('*' = the meta shard)."""
        with self._lock:
            fallback = None
            for name, rec in self._registry.items():
                if watch_kind in rec.get("kinds", []):
                    return name
                if "*" in rec.get("kinds", []):
                    fallback = name
        if fallback is None:
            raise NotFound(f"no shard owns kind {watch_kind!r}")
        return fallback

    def watch_targets(self, kinds: list[str]) -> dict[str, list[str]]:
        """{shard name: [watch kinds]} for a /watch request — the
        router dials each target once, multiplexed."""
        out: dict[str, list[str]] = {}
        for kind in kinds:
            if kind == "pods":
                for name in self.pod_shard_names():
                    out.setdefault(name, [])
                    if "pods" not in out[name]:
                        out[name].append("pods")
            else:
                owner = self._kind_owner(kind)
                out.setdefault(owner, [])
                if kind not in out[owner]:
                    out[owner].append(kind)
        return out

    # ------------- generic routing -------------

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in _SHARD_ONLY_METHODS:
            # these act on ONE shard's store; the router cannot pick a
            # target for them, and silently hitting the meta shard
            # would corrupt a manual rebalance — fail loudly with the
            # supported surface instead
            def reject(*_args, _m=name):
                raise ValueError(
                    f"{_m} is a shard-process verb: call the shard's "
                    "URL directly, or drive moves through the "
                    "router's rebalance_segment")

            return reject
        if name.startswith("fabric_"):
            # registry/ring/topology verbs live on the state shard; the
            # router forwards them so admins drive the fabric through
            # the same URL everything else uses
            return getattr(self.state, name)
        if name in _NODE_METHODS:
            return self._forwarder(self._kind_owner("nodes"), name)
        if name in _EVENT_METHODS:
            return self._forwarder(self._kind_owner("events"), name)
        if not name.startswith("watch") and hasattr(Hub, name):
            return self._forwarder(self._kind_owner("__meta__"), name)
        raise AttributeError(name)

    def _forwarder(self, shard: str, method: str):
        def fwd(*args, _s=shard, _m=method):
            return self._invoke(_s, _m, *args)

        fwd.__name__ = method
        return fwd

    # ------------- pods (ring-routed) -------------

    # how long a pod write chases a migrating segment before parking:
    # the flip itself takes milliseconds, the budget rides out a state
    # failover happening mid-migrate
    STALE_RING_DEADLINE_S = 5.0

    def _invoke_ns(self, method: str, namespace: str, *args):
        """Namespace-routed pod write with stale-ring fencing: a
        StaleRing verdict from the shard (the slot is frozen mid-export
        or the ring flipped under us) re-reads the ring and retries the
        CURRENT owner — a write is redirected, never committed onto a
        segment that is about to be dropped."""
        end = time.monotonic() + self.STALE_RING_DEADLINE_S
        while True:
            with self._migrate_lock:
                try:
                    return self._invoke(self._pod_shard_name(namespace),
                                        method, *args)
                except StaleRing as e:
                    err = e
            self.stale_ring_retries += 1
            self.ring(fresh=True)
            if time.monotonic() >= end:
                raise Unavailable(
                    f"{method}: segment for {namespace!r} still "
                    f"migrating ({err})") from None
            time.sleep(0.02)

    def create_pod(self, pod) -> None:
        self._invoke_ns("create_pod", pod.metadata.namespace, pod)

    def update_pod(self, pod) -> None:
        self._invoke_ns("update_pod", pod.metadata.namespace, pod)

    def bind(self, pod, node_name: str, epoch=None,
             lease_name: str = "kube-scheduler") -> None:
        self._invoke_ns("bind", pod.metadata.namespace, pod, node_name,
                        epoch, lease_name)

    def patch_pod_condition(self, pod, condition, nominated_node=None,
                            epoch=None,
                            lease_name: str = "kube-scheduler") -> None:
        self._invoke_ns("patch_pod_condition", pod.metadata.namespace,
                        pod, condition, nominated_node, epoch,
                        lease_name)

    def _invoke_uid(self, method: str, uid: str, *args,
                    missing_ok: bool = False):
        """Uid-routed pod write: any holder may answer the READ (the
        probe), but the WRITE routes by the ring like every
        namespace-routed verb. During a migrate's overlap window both
        shards hold a copy — committing on "whichever copy accepts"
        would let a pre-flip target swallow a delete that the
        rollback's drop then discards (resurrecting the pod), so only
        the ring-assigned owner commits; a frozen source parks the
        write until the flip or the abort resolves it."""
        end = time.monotonic() + self.STALE_RING_DEADLINE_S
        while True:
            with self._migrate_lock:
                pod = None
                for name in self.pod_shard_names():
                    pod = self._invoke(name, "get_pod", uid)
                    if pod is not None:
                        break
                if pod is None:
                    if missing_ok:
                        return None
                    raise NotFound(f"Pod {uid}")
                try:
                    return self._invoke(
                        self._pod_shard_name(pod.metadata.namespace),
                        method, uid, *args)
                except StaleRing as e:
                    err = e
                except NotFound:
                    # a stray copy answered the probe but the
                    # ring-assigned owner has no such pod: the owner's
                    # verdict is authoritative (the stray reconciles
                    # away)
                    if missing_ok:
                        return None
                    raise
            self.stale_ring_retries += 1
            self.ring(fresh=True)
            if time.monotonic() >= end:
                raise Unavailable(
                    f"{method}: pod {uid} still migrating "
                    f"({err})") from None
            time.sleep(0.02)

    def delete_pod(self, uid: str, epoch=None,
                   lease_name: str = "kube-scheduler") -> None:
        self._invoke_uid("delete_pod", uid, epoch, lease_name)

    def delete_pods(self, uids: list[str], epoch=None,
                    lease_name: str = "kube-scheduler") -> list[str]:
        """Batched eviction wave over the ring: each uid still routes to
        its owning shard process (ring + probe, StaleRing-retried), so
        the wave degrades to per-uid calls across shard boundaries —
        explicit here because __getattr__'s meta-shard forward would
        silently delete nothing. A NotFound victim is skipped (already
        gone), matching the single-hub wave."""
        gone: list[str] = []
        for uid in uids:
            try:
                self._invoke_uid("delete_pod", uid, epoch, lease_name)
                gone.append(uid)
            except NotFound:
                pass
        return gone

    def get_pod(self, uid: str):
        for name in self.pod_shard_names():
            p = self._invoke(name, "get_pod", uid)
            if p is not None:
                return p
        return None

    def set_pod_claim_statuses(self, uid: str, statuses) -> None:
        self._invoke_uid("set_pod_claim_statuses", uid, statuses,
                         missing_ok=True)

    def clear_nominated_node(self, uid: str, epoch=None,
                             lease_name: str = "kube-scheduler") -> None:
        self._invoke_uid("clear_nominated_node", uid, epoch, lease_name,
                         missing_ok=True)

    def list_pods(self) -> list:
        # dedupe by uid keeping the newest revision: a rebalance's
        # copy-before-drop overlap may briefly list a pod on two shards
        best: dict[str, object] = {}
        for name in self.pod_shard_names():
            for p in self._invoke(name, "list_pods"):
                cur = best.get(p.metadata.uid)
                if cur is None or p.metadata.resource_version \
                        >= cur.metadata.resource_version:
                    best[p.metadata.uid] = p
        return list(best.values())

    # ------------- merged reads -------------

    def list_changes(self, since_rv: int,
                     kinds: tuple = ("pods", "nodes")) -> dict:
        """Merged incremental LIST, consistency rv read from the shared
        allocator BEFORE the shard scan (the ShardedHub discipline: a
        commit landing on an already-scanned shard is re-examined next
        pass, never skipped)."""
        rv0 = self.state.rv.last()
        merged: list[dict] = []
        for shard, shard_kinds in self.watch_targets(list(kinds)).items():
            res = self._invoke(shard, "list_changes", since_rv,
                               tuple(shard_kinds))
            if res.get("too_old"):
                return {"too_old": True,
                        "compacted_rv": res["compacted_rv"], "rv": rv0}
            merged.extend(res["changes"])
        merged.sort(key=lambda c: c["rv"])
        return {"too_old": False, "rv": rv0, "changes": merged}

    def get_journal_stats(self) -> dict:
        kinds: dict = {}
        shards: dict = {}
        wal = False
        cap = 0
        with self._lock:
            names = list(self._registry)
        for name in names:
            try:
                st = self._invoke(name, "get_journal_stats")
            except Unavailable:
                shards[name] = {"error": "unavailable"}
                continue
            wal = wal or st.get("wal", False)
            cap = max(cap, st.get("capacity", 0))
            for kind, ks in st.get("kinds", {}).items():
                agg = kinds.get(kind)
                if agg is None:
                    kinds[kind] = dict(ks)
                else:
                    agg["depth"] += ks["depth"]
                    agg["compacted_rv"] = max(agg["compacted_rv"],
                                              ks["compacted_rv"])
                    agg["last_rv"] = max(agg["last_rv"], ks["last_rv"])
            shards[name] = {
                "kinds": sorted(st.get("kinds", {})),
                "depth": sum(k["depth"]
                             for k in st.get("kinds", {}).values()),
                "compacted_rv": max(
                    [k["compacted_rv"]
                     for k in st.get("kinds", {}).values()],
                    default=0),
                "commits": st.get("commits", 0),
                "rv": st.get("rv", 0),
                "watchers": st.get("watchers", {}),
            }
        return {"rv": self.state.rv.last(), "capacity": cap,
                "wal": wal, "kinds": kinds, "shards": shards}

    def shard_map(self) -> dict:
        from kubernetes_tpu.hubserver import WATCH_KINDS

        out = {}
        for kind in WATCH_KINDS:
            if kind == "pods":
                out["pods"] = self.pod_shard_names()
            else:
                try:
                    out[kind] = self._kind_owner(kind)
                except NotFound:
                    out[kind] = None
        return out

    @property
    def current_rv(self) -> int:
        return self.state.rv.last()

    # ------------- ring rebalance -------------

    def rebalance_segment(self, slots: list, to_shard: str) -> dict:
        """Move ring ``slots`` onto ``to_shard`` with zero dropped
        resume points and zero events:

        1. copy the segment's pods to the target (``import_segment``
           WAL-attaches them with their original uids/revisions — a
           concurrent LIST sees duplicates, which every client dedups
           by uid+rv, never a hole);
        2. CAS the ring map on the state shard (epoch bump);
        3. drop the segment from the sources (WAL detach; their journal
           rings keep the pre-move history, so a watch resuming across
           the move still gets the complete per-shard suffixes).

        The migrate lock serializes THIS router's writes around the
        flip; writes from OTHER routers are fenced shard-side — a
        frozen/deposed slot answers StaleRing and the writer re-reads
        the ring — so two routers can never split-brain a segment.

        The flip itself is **complete-or-rollback**: the ring CAS on
        the state quorum either commits (we finish with the drop) or
        it doesn't (we drop the target's copy and thaw the source).
        When the CAS outcome is ambiguous — the state leader was
        ``kill -9``'d mid-CAS, or a retried CAS answers False because
        our FIRST attempt already committed — the ring itself is the
        verdict: we re-read it from the new quorum and match it
        against our proposed layout. A source dying mid-drop leaves a
        stale copy that its restart reconciles away
        (``reconcile_ring``)."""
        if to_shard not in self.pod_shard_names() \
                and to_shard not in self._registry:
            raise NotFound(f"unknown target shard {to_shard!r}")
        with self._migrate_lock:
            ring = self.ring(fresh=True)
            size = len(ring["slots"])
            moves: dict[str, list[int]] = {}
            for s in slots:
                if not 0 <= s < size:
                    raise ValueError(f"slot {s} outside ring size {size}")
                src = ring["slots"][s]
                if src != to_shard:
                    moves.setdefault(src, []).append(s)
            moved = {}
            moved_slots: list[int] = []
            for src, sl in moves.items():
                # export freezes the slots on the source (StaleRing to
                # concurrent writers) atomically with the copy
                pods = self._invoke(src, "export_segment", sl, size)
                self._invoke(to_shard, "import_segment", pods, sl, size)
                moved[src] = len(pods)
                moved_slots.extend(sl)
            new_slots = list(ring["slots"])
            for s in slots:
                new_slots[s] = to_shard
            new_ring = {"epoch": ring["epoch"] + 1, "slots": new_slots}
            try:
                committed = bool(self.state.fabric_set_ring(
                    new_ring, ring["epoch"]))
            except Unavailable:
                committed = False
            resolved = None
            if not committed:
                # ambiguous or lost: the quorum's ring is the verdict —
                # judged on OUR slots only, because an unrelated
                # rebalance committing concurrently moves the epoch and
                # other slots without saying anything about ours
                resolved = self._ring_verdict(slots, to_shard,
                                              ring["epoch"] + 1)
                committed = resolved is not None
            if not committed:
                # rolled back: remove the target's copy, thaw the
                # sources — the segment never moved, parked writers
                # land back on the original owner
                for src, sl in moves.items():
                    try:
                        self._invoke(to_shard, "drop_segment", sl, size)
                    except Unavailable:
                        pass   # target restart reconciles the stray copy
                    try:
                        self._invoke(src, "abort_export", sl, size)
                    except Unavailable:
                        pass   # FROZEN_TTL_S + heartbeat thaw it
                raise Conflict("ring epoch moved under the rebalance "
                               "(or the CAS lost); rolled back — "
                               "re-read and retry")
            with self._lock:
                self._ring = resolved or new_ring
                self._ring_ts = time.monotonic()
            pending = []
            for src, sl in moves.items():
                try:
                    self._invoke(src, "drop_segment", sl, size)
                except Unavailable:
                    # the source died mid-move: its restart replays the
                    # WAL (resurrecting the stale copy) and then
                    # reconciles against the flipped ring
                    pending.append(src)
            return {"epoch": new_ring["epoch"], "moved": moved,
                    "pending_drops": pending}

    def _ring_verdict(self, slots: list, to_shard: str,
                      want_epoch: int,
                      deadline_s: float = 10.0) -> dict | None:
        """Did OUR move land? Re-read the quorum's ring (riding out a
        failover) and check that every moved slot points at our target
        with the epoch at least ours: a retried CAS that answered
        False after our first attempt committed, or a leader killed
        mid-CAS, both resolve here. Returns the current ring when the
        move is in effect, None when it is not (roll back)."""
        end = time.monotonic() + deadline_s
        while True:
            try:
                cur = self.state.fabric_ring()
            except Unavailable:
                if time.monotonic() >= end:
                    raise
                time.sleep(0.2)
                continue
            if cur["epoch"] >= want_epoch \
                    and all(cur["slots"][s] == to_shard
                            for s in slots):
                return cur
            return None

    # ------------- lifecycle -------------

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        try:
            self.state.close()
        except Exception:  # noqa: BLE001
            pass
