"""Control-plane fabric: hub scale-out for million-user traffic.

Three pillars (ROADMAP item 3):

* :mod:`kubernetes_tpu.fabric.codec` — a compact binary wire codec
  (length-prefixed msgpack-style framing, versioned, negotiated
  per-connection with JSON fallback) replacing JSON on the
  hubserver/hubclient hot path.
* :mod:`kubernetes_tpu.fabric.sharded` — :class:`ShardedHub`, the hub
  sharded by kind (and namespace-hash within the pod kind) over the
  existing rv journal; each shard owns its rings/WAL behind a thin
  router that preserves the single-hub ``Hub``/``RemoteHub`` API,
  fencing epochs, and cross-shard watch-resume semantics.
* :mod:`kubernetes_tpu.fabric.relay` — the watch relay tree: relay
  nodes subscribe upstream once per kind set and fan events out to
  thousands of downstream reflectors with per-subscriber resume
  cursors and backpressure-aware slow-subscriber eviction.

:mod:`kubernetes_tpu.fabric.fanout` drives the 10k-client smoke
(``bench.py --fanout-smoke``).

Submodules other than ``codec`` load lazily (PEP 562): the transport
layer (hubserver/hubclient) imports ``fabric.codec``, and the relay
imports the transport — eager re-exports here would close that loop.
"""

from kubernetes_tpu.fabric import codec  # noqa: F401
from kubernetes_tpu.fabric.codec import (  # noqa: F401
    CODEC_BINARY,
    CODEC_JSON,
    decode,
    encode,
    registry_fingerprint,
)

_LAZY = {
    "ShardedHub": ("kubernetes_tpu.fabric.sharded", "ShardedHub"),
    "RelayCore": ("kubernetes_tpu.fabric.relay", "RelayCore"),
    "RelayServer": ("kubernetes_tpu.fabric.relay", "RelayServer"),
    "run_fanout_smoke": ("kubernetes_tpu.fabric.fanout",
                         "run_fanout_smoke"),
    # out-of-process fabric (ISSUE 11): shard processes, the shared-
    # state shard, the stateless router, and the local supervisor
    "StateCore": ("kubernetes_tpu.fabric.cluster", "StateCore"),
    "ProcShardHub": ("kubernetes_tpu.fabric.cluster", "ProcShardHub"),
    "ClusterClient": ("kubernetes_tpu.fabric.cluster", "ClusterClient"),
    "RouterServer": ("kubernetes_tpu.fabric.router", "RouterServer"),
    "spawn_local_cluster": ("kubernetes_tpu.fabric.supervisor",
                            "spawn_local_cluster"),
    "run_fanout_smoke_procs": ("kubernetes_tpu.fabric.fanout",
                               "run_fanout_smoke_procs"),
    # replicated state core (ISSUE 13): the Raft-lite quorum for
    # rv / fencing / ring, and its leader-routing client
    "StateReplica": ("kubernetes_tpu.fabric.replica", "StateReplica"),
    "ReplicaClient": ("kubernetes_tpu.fabric.replica", "ReplicaClient"),
    "make_state_client": ("kubernetes_tpu.fabric.replica",
                          "make_state_client"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(target[0]), target[1])
