"""The stateless bin1 router: the fabric's single-hub face.

One (or many — it holds no state beyond connection handles and a TTL'd
ring cache) process speaking hubserver's exact wire in front of the
shard processes:

* ``POST /call`` — the inherited hubserver handler, dispatching into a
  :class:`~kubernetes_tpu.fabric.cluster.ClusterClient`: by-kind verbs
  go whole to their shard, pod verbs route on the namespace-crc32
  ring, ``rv.*``/``leases.*`` go to the shared-state shard. Codec
  negotiation, typed errors, and retries are the stock machinery.
* ``GET /watch`` — a **pass-through merge**: one upstream stream per
  owning shard (``≤ (router watch connections)`` sockets per shard
  process, however many clients hang downstream of the relay tree),
  every event re-framed with its source-shard tag (``sh``), and ONE
  downstream sync marker once every upstream has synced, carrying the
  per-shard sync map. With ``cursors=`` the router dials each shard at
  that shard's own resume point — the composite-cursor discipline that
  makes cross-shard resume exact (see fabric.cluster's module doc).
  The router never buffers or heals streams: an upstream dying cuts
  the downstream, whose client resumes; statelessness IS the
  availability story.
* ``GET /topology`` — the served relay/router/shard map (open, cached
  briefly): clients and relays discover and re-parent through it
  instead of being pointed by flag.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
import urllib.error
import urllib.request

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.fabric.cluster import ClusterClient
from kubernetes_tpu.fabric.flowcontrol import watch_priority
from kubernetes_tpu.hub import NotFound, TooManyRequests
from kubernetes_tpu.hubserver import (
    FRAMES_CONTENT_TYPE,
    _Handler,
    make_stream_writers,
    parse_watch_query,
)


class _RouterHandler(_Handler):
    server_version = "kubernetes-tpu-router/1"

    # do_POST is inherited: self.hub is the ClusterClient, which is
    # Hub-shaped — /call routing IS the facade's routing.

    @property
    def cluster(self) -> ClusterClient:
        return self.server.hub  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(self.path)
        path = parsed.path
        if path in ("/healthz", "/livez"):
            self._text(200, "ok")
            return
        if path == "/metrics":
            from kubernetes_tpu.telemetry.fleet import (
                hub_metrics_text,
                process_identity_text,
            )

            body = process_identity_text(
                "router", self.server.server_address[1]) \
                + hub_metrics_text(self.cluster)
            flow = getattr(self.server, "flow", None)
            if flow is not None:
                body += flow.metrics_text()
            self._text(200, body)
            return
        if path == "/topology":
            topo = self.server.topology()  # type: ignore[attr-defined]
            self._json(200, topo)
            return
        if path != "/watch":
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        q = parse_qs(parsed.query)
        params, err = parse_watch_query(
            q, self.server.codecs)  # type: ignore[attr-defined]
        if params is None:
            self._json(400, {"error": "ValueError", "message": err})
            return
        srv = self.server
        limit = getattr(srv, "watch_limit", None)
        if limit is None:
            self._watch_passthrough(params)
            return
        # admission before the expensive part: each passthrough opens
        # one upstream socket per owning shard, so NEW best-effort
        # subscriptions shed at the bound — existing streams (and any
        # attributed priority) are never cut to make room
        priority = watch_priority(q.get("identity", [""])[0])
        with srv.watch_lock:                # type: ignore[attr-defined]
            if priority == "best-effort" \
                    and srv.watch_active >= limit:
                srv.watch_sheds += 1
                shed = True
            else:
                srv.watch_active += 1
                shed = False
        if shed:
            e = TooManyRequests(
                "router watch capacity: best-effort subscriptions "
                "shed", retry_after=1.0)
            self._json(429, {"error": "TooManyRequests",
                             "message": str(e)},
                       headers={"Retry-After":
                                f"{e.retry_after:.3f}"})
            return
        try:
            self._watch_passthrough(params)
        finally:
            with srv.watch_lock:            # type: ignore[attr-defined]
                srv.watch_active -= 1

    # ------------- the pass-through merge -------------

    def _dial_upstreams(self, params):
        """One upstream /watch per owning shard, each multiplexed over
        that shard's subset of the requested kinds and resumed at that
        shard's cursor. Returns [(shard, response)] or raises with the
        downstream answer already sent."""
        cluster = self.cluster
        try:
            targets = cluster.watch_targets(list(params.kinds))
        except NotFound as e:
            self._json(400, {"error": "ValueError", "message": str(e)})
            return None
        opened: list[tuple[str, object]] = []
        try:
            for shard, kinds in sorted(targets.items()):
                base = cluster.shard_url(shard)
                url = f"{base}/watch?kinds={','.join(kinds)}"
                since = None
                if params.cursors is not None:
                    since = params.cursors.get(shard, params.since_rv)
                elif params.since_rv is not None:
                    since = params.since_rv
                if since is not None:
                    url += f"&since_rv={since}"
                else:
                    url += f"&replay={'1' if params.replay else '0'}"
                url += (f"&codec={binwire.CODEC_BINARY}"
                        f"&fp={binwire.registry_fingerprint()}")
                opened.append((shard, urllib.request.urlopen(
                    url, timeout=30.0)))
            return opened
        except urllib.error.HTTPError as e:
            for _, r in opened:
                self._close_quiet(r)
            if e.code == 410:
                try:
                    payload = json.loads(e.read())
                except (ValueError, OSError):
                    payload = {}
                self._json(410, {
                    "error": "RvTooOld",
                    "message": payload.get("message", "compacted"),
                    "compacted_rv": payload.get("compacted_rv", 0)})
            else:
                try:
                    body = e.read().decode("utf-8", "replace")[:200]
                except OSError:
                    body = ""
                self._json(502, {"error": "Upstream",
                                 "message": f"shard HTTP {e.code}: "
                                            f"{body}"})
            self._close_quiet(e)
            return None
        except OSError as e:
            for _, r in opened:
                self._close_quiet(r)
            # the shard may have restarted on a new port: refresh the
            # registry so the CLIENT'S retry dials the fresh URL
            try:
                cluster.refresh_shards()
            except Exception:  # noqa: BLE001 — state shard down too
                pass
            self._json(503, {"error": "Unavailable",
                             "message": f"shard unreachable: {e}"})
            return None

    @staticmethod
    def _close_quiet(resp) -> None:
        try:
            resp.close()
        except OSError:
            pass

    def _watch_passthrough(self, params) -> None:
        upstreams = self._dial_upstreams(params)
        if upstreams is None:
            return
        events: queue.Queue = queue.Queue(maxsize=100000)
        _DONE = object()

        def read_upstream(shard: str, resp) -> None:
            """Decode one shard's stream into the merge queue. Values
            pass through UNTOUCHED (bin1 frames decode to real objects,
            JSON lines to wire dicts — the downstream writer and every
            client's from_wire accept either), so the router never pays
            an object re-materialization."""
            try:
                ctype = resp.headers.get("Content-Type", "")
                if ctype.startswith(FRAMES_CONTENT_TYPE):
                    while True:
                        payload = binwire.read_frame(resp)
                        if payload is None:
                            return
                        events.put((shard, binwire.decode(payload)))
                else:
                    for raw in resp:
                        line = raw.strip()
                        if line:
                            events.put((shard, json.loads(line)))
            except (OSError, ValueError, AttributeError,
                    http.client.HTTPException):
                # a shard dying mid-frame surfaces IncompleteRead (an
                # HTTPException) from the exact-length frame read —
                # the same taxonomy hubclient's consume() handles
                pass
            finally:
                events.put((shard, _DONE))

        readers = [threading.Thread(target=read_upstream, args=(s, r),
                                    daemon=True,
                                    name=f"router-watch-{s}")
                   for s, r in upstreams]
        for t in readers:
            t.start()

        self.send_response(200)
        self.send_header("Content-Type",
                         FRAMES_CONTENT_TYPE if params.use_bin
                         else "application/jsonlines")
        if params.use_bin:
            self.send_header(binwire.WIRE_HEADER, binwire.offer())
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        write_obj, write_event = make_stream_writers(
            self.wfile, params.use_bin, params.mux)

        synced: dict[str, int] = {}
        sync_sent = False
        last_write = time.monotonic()
        try:
            while not self.server.stopping:  # type: ignore[attr-defined]
                # time-based keepalive: upstream keepalives arrive once
                # per shard per second and are swallowed below, so the
                # queue-empty branch alone would never fire — and a
                # silent downstream wedges its client's close() and
                # dead-peer detection
                if time.monotonic() - last_write >= 1.0:
                    write_obj({})
                    last_write = time.monotonic()
                try:
                    shard, ev = events.get(timeout=1.0)
                except queue.Empty:
                    continue
                if ev is _DONE:
                    # a shard stream died (kill -9, restart, cut): a
                    # partial fabric stream must never masquerade as a
                    # complete one — cut downstream, the client resumes
                    # with its per-shard cursors
                    return
                if not ev:
                    continue                 # upstream keepalive
                if ev.get("synced"):
                    if shard not in synced:
                        synced[shard] = ev.get("rv") or 0
                        if not sync_sent and len(synced) == len(upstreams):
                            # every shard's replay (LIST or journal
                            # suffix) has drained: one merged marker,
                            # carrying the per-shard cursor seeds
                            write_obj({"synced": True,
                                       "rv": max(synced.values(),
                                                 default=0),
                                       "shards": dict(synced)})
                            sync_sent = True
                            last_write = time.monotonic()
                    continue
                # replay events flow through BEFORE the merged sync
                # marker; clients treat a resumed stream's pre-sync
                # events as ordinary incremental events and a replay's
                # as LIST entries — exactly the single-hub contract
                write_event(ev.get("kind") or params.kinds[0],
                            ev.get("type"), ev.get("rv") or 0,
                            ev.get("old"), ev.get("new"),
                            ev.get("trace"), shard)
                last_write = time.monotonic()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            for _, r in upstreams:
                self._close_quiet(r)


class RouterServer:
    """``RouterServer(state_url).start()`` → the fabric's single-hub
    wire on ``address``; point RemoteHub clients, relays, schedulers,
    and kubemark feeders at it."""

    def __init__(self, state_url: str, host: str = "127.0.0.1",
                 port: int = 0, name: str = "router-0",
                 codecs: tuple[str, ...] = (binwire.CODEC_BINARY,
                                            binwire.CODEC_JSON),
                 cluster: ClusterClient | None = None,
                 topology_ttl_s: float = 1.0,
                 flow=None, watch_limit: int | None = None):
        import os

        from http.server import ThreadingHTTPServer

        self.cluster = cluster or ClusterClient(state_url)
        self.name = name
        self.flow = flow
        self._httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.hub = self.cluster        # type: ignore[attr-defined]
        self._httpd.codecs = codecs           # type: ignore[attr-defined]
        self._httpd.stopping = False          # type: ignore[attr-defined]
        # flow control: ``flow`` bounds /call admission (the inherited
        # hubserver handler reads it); ``watch_limit`` bounds live
        # passthrough streams — past it, new best-effort watch
        # subscriptions answer 429 (None = legacy unbounded)
        self._httpd.flow = flow               # type: ignore[attr-defined]
        self._httpd.watch_limit = watch_limit  # type: ignore[attr-defined]
        self._httpd.watch_active = 0          # type: ignore[attr-defined]
        self._httpd.watch_sheds = 0           # type: ignore[attr-defined]
        self._httpd.watch_lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.topology = self._topology  # type: ignore[attr-defined]
        self._topo_cache: tuple[float, dict] | None = None
        self._topo_ttl = topology_ttl_s
        self._topo_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        # announce ourselves so the topology map names the router(s)
        try:
            self.cluster.state.fabric_register_router(
                name, self.address, os.getpid())
        except Exception:  # noqa: BLE001 — the state shard may still be
            pass           # coming up; registration is best-effort

    def _topology(self) -> dict:
        now = time.monotonic()
        with self._topo_lock:
            if self._topo_cache is not None \
                    and now - self._topo_cache[0] < self._topo_ttl:
                return self._topo_cache[1]
        try:
            topo = self.cluster.state.fabric_topology()
        except Exception:
            # state quorum mid-election: serve the stale map rather
            # than cutting discovery — wiring degrades, never vanishes
            with self._topo_lock:
                if self._topo_cache is not None:
                    return self._topo_cache[1]
            raise
        with self._topo_lock:
            self._topo_cache = (now, topo)
        return topo

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def watch_sheds(self) -> int:
        """Best-effort watch subscriptions answered 429 (watch_limit)."""
        return self._httpd.watch_sheds    # type: ignore[attr-defined]

    def start(self) -> "RouterServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="fabric-router")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.stopping = True           # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.cluster.close()


def fetch_topology(url: str, timeout: float = 5.0) -> dict:
    """GET a served topology map from a router (``/topology``); falls
    back to the state shard's ``fabric_topology`` verb over /call so
    either endpoint works."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/topology",
                                    timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError:
        from kubernetes_tpu.hubclient import RemoteHub

        client = RemoteHub(url, timeout=timeout)
        try:
            return client.fabric_topology()
        finally:
            client.close()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="kubernetes_tpu.fabric.router",
        description="stateless fabric router (multi-host deployment: "
                    "one or more per cluster)")
    ap.add_argument("--state", required=True,
                    help="shared-state shard URL")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--name", default="router-0")
    args = ap.parse_args(argv)
    server = RouterServer(args.state, host=args.host, port=args.port,
                          name=args.name).start()
    # the supervisor parses this line to learn the bound port
    print(f"LISTENING {server.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
