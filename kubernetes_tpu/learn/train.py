"""Offline MLP trainer for the learned scorer — pure JAX, deterministic
given a seed.

Two phases, following the PAPERS shape (behavior-clone the incumbent
policy, then improve it from recorded outcomes):

1. **Behavior cloning**: full-batch Adam on MSE between the MLP output
   and the hand-tuned aggregate (rescaled to [0, 100]) — the warm start
   that guarantees the scorer begins AT the incumbent policy instead of
   at noise.
2. **Reward-weighted fine-tune**: targets nudged by each example's
   outcome advantage (reward minus the batch mean — evictions, slow
   binds, and domain crowding push a placement's target down, clean
   fast placements push it up), samples weighted by |advantage| so the
   informative tail dominates. This is reward-weighted regression, not
   RL-with-rollouts: the cluster is not available for on-policy
   exploration, the replay is.

Everything (init, shuffling-free full-batch steps, Adam state) is
derived from the seed; two runs with the same seed and dataset produce
bit-identical checkpoints — the property the A/B harness and the
regression tests lean on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.learn.replay import ReplayDataset
from kubernetes_tpu.ops.learned import (
    MAX_SCORE,
    NUM_FEATURES,
    hand_weight_vector,
    mlp_apply,
)


@dataclass
class TrainConfig:
    hidden: tuple = (8,)
    seed: int = 0
    bc_epochs: int = 300
    ft_epochs: int = 150
    lr: float = 0.03
    ft_lr: float = 0.005
    # score points a one-unit outcome advantage moves the target by
    ft_gain: float = 25.0
    meta: dict = field(default_factory=dict)


def init_params(seed: int, hidden: tuple = (8,),
                num_features: int = NUM_FEATURES):
    """He-initialized ((W, b), ...) layer stack, scalar head."""
    key = jax.random.PRNGKey(seed)
    sizes = (num_features,) + tuple(hidden) + (1,)
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        scale = float(np.sqrt(2.0 / sizes[i]))
        w = jax.random.normal(sub, (sizes[i], sizes[i + 1]),
                              jnp.float32) * scale
        params.append((w, jnp.zeros((sizes[i + 1],), jnp.float32)))
    return tuple(params)


def identity_params():
    """A single linear layer reproducing the hand-tuned no-topology
    aggregate (rescaled to [0, 100]): the differential-test fixture —
    at any positive weight it only rescales the aggregate on
    topology-free batches, so placements match the baseline exactly."""
    w = np.zeros((NUM_FEATURES, 1), np.float32)
    hand = hand_weight_vector()      # live default_weights, feature order
    # features are score/100, so out = sum(w_i * s_i) / sum(w) in [0,100]
    w[:, 0] = hand * (MAX_SCORE / hand.sum())
    return ((w, np.zeros((1,), np.float32)),)


def _adam_step(params, grads, m, v, t, lr, b1=0.9, b2=0.999, eps=1e-8):
    m = jax.tree.map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
    v = jax.tree.map(lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
    mh = jax.tree.map(lambda a: a / (1 - b1 ** t), m)
    vh = jax.tree.map(lambda a: a / (1 - b2 ** t), v)
    params = jax.tree.map(
        lambda p, a, b: p - lr * a / (jnp.sqrt(b) + eps), params, mh, vh)
    return params, m, v


def _fit(params, x, y, w, epochs, lr):
    """Full-batch weighted-MSE Adam; returns (params, first_loss,
    last_loss)."""

    def loss_fn(p):
        pred = mlp_apply(p, x)
        return jnp.mean(w * (pred - y) ** 2)

    step = jax.jit(jax.value_and_grad(loss_fn))
    m = jax.tree.map(jnp.zeros_like, params)
    v = jax.tree.map(jnp.zeros_like, params)
    first = last = None
    for t in range(1, max(epochs, 0) + 1):
        loss, grads = step(params)
        params, m, v = _adam_step(params, grads, m, v, t, lr)
        if first is None:
            first = float(loss)
        last = float(loss)
    return params, first, last


def train(ds: ReplayDataset, cfg: Optional[TrainConfig] = None):
    """Returns (params, info): params a ((W, b), ...) numpy stack ready
    for learn.checkpoint.save_checkpoint, info the training record that
    lands in the checkpoint meta."""
    cfg = cfg or TrainConfig()
    if len(ds) == 0:
        raise ValueError("empty replay dataset")
    x = jnp.asarray(ds.x, jnp.float32)
    y = jnp.asarray(ds.y, jnp.float32)
    ones = jnp.ones_like(y)
    params = init_params(cfg.seed, cfg.hidden, ds.x.shape[1])
    params, bc_first, bc_last = _fit(params, x, y, ones,
                                     cfg.bc_epochs, cfg.lr)
    info = {
        "seed": cfg.seed,
        "hidden": list(cfg.hidden),
        "examples": int(len(ds)),
        "bc_epochs": cfg.bc_epochs,
        "bc_loss_first": round(bc_first or 0.0, 4),
        "bc_loss_last": round(bc_last or 0.0, 4),
    }
    info.update(cfg.meta)
    if cfg.ft_epochs > 0:
        adv = jnp.asarray(ds.reward, jnp.float32)
        adv = adv - jnp.mean(adv)
        target = jnp.clip(y + cfg.ft_gain * adv, 0.0, MAX_SCORE)
        weight = 1.0 + jnp.abs(adv)
        params, ft_first, ft_last = _fit(params, x, target, weight,
                                         cfg.ft_epochs, cfg.ft_lr)
        info.update(ft_epochs=cfg.ft_epochs,
                    ft_loss_first=round(ft_first or 0.0, 4),
                    ft_loss_last=round(ft_last or 0.0, 4))
    params_np = tuple((np.asarray(w, np.float32), np.asarray(b, np.float32))
                      for w, b in params)
    return params_np, info
