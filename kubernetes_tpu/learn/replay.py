"""Replay dataset builder: flight-recorder trace exports + journal/WAL
-> training examples for the learned scorer.

Example = (feature row, behavior-cloning target, outcome reward):

- **Features** come straight from the trace export (format v2): each
  cycle line carries per-pod placement rows with the CHOSEN node's
  feature vector as the device program computed it
  (``BatchResult.chosen_feat``) — training sees exactly the inference
  distribution, no host re-derivation drift.
- **Behavior-cloning target** is the hand-tuned weighted sum over the
  FEATURE-EXPRESSIBLE plugin scores, reconstructed from the feature row
  itself (the "Learning to Score" warm start: clone the weighted
  combination, then move off it). The exported winning aggregate is
  deliberately NOT the target: it also carries topology/IPA/host terms
  the feature row cannot express, so on topology-heavy workloads it
  saturates any fixed rescale at the clip and the BC fit degenerates to
  a pinned constant. It still rides the dataset as ``agg_score`` — the
  analysis column and a future richer-feature target.
- **Outcome rewards** are harvested downstream from the hub's
  journal/WAL (kubernetes_tpu.storage): a placement whose pod was later
  evicted/preempted (a bound pod DELETE) is down-weighted, slow
  time-to-bind (first trace appearance -> bind cycle) and
  topology-domain crowding (bound-count imbalance of the chosen node's
  zone/hostname domain at replay end) shade the reward around 1.0.

Everything is host-side numpy over JSON lines — no device work; a few
hundred thousand examples build in seconds.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

import numpy as np

from kubernetes_tpu.ops.learned import NUM_FEATURES, hand_weight_vector

logger = logging.getLogger("kubernetes_tpu.learn")

# oldest export format this reader accepts: v2 introduced the placement
# rows + feature vectors this dataset is built from. v3 (the "alt"
# top-K alternative scores) is additive, so v2 rows stay valid input —
# the reader keys on its own floor, NOT the writer's EXPORT_VERSION,
# so bumping the writer never silently discards yesterday's traces.
REPLAY_MIN_VERSION = 2


def bc_targets(x: np.ndarray) -> np.ndarray:
    """[M] behavior-cloning targets in [0, 100]: the hand-tuned
    weighted sum over the feature-expressible plugin scores,
    reconstructed from the feature rows (features are score/100, so
    (x @ w) * 100 / sum(w) is exactly the rescaled aggregate — no
    clipping, no topology contamination)."""
    w = hand_weight_vector()
    return ((x @ w) * (100.0 / w.sum())).astype(np.float32)

EVICT_PENALTY = 0.25          # reward factor for later-evicted placements
SLOW_BIND_SHADE = 0.25        # shade per unit of above-median bind time
CROWDING_SHADE = 0.5          # shade per unit of above-mean domain count

ZONE_LABEL = "topology.kubernetes.io/zone"
HOSTNAME_LABEL = "kubernetes.io/hostname"


@dataclass
class ReplayDataset:
    """x [M, F] float32 features; y [M] behavior-clone targets in
    [0, 100]; reward [M] outcome weights around 1.0; agg_score [M] the
    exported winning aggregate (analysis only — includes topology/host
    terms the features cannot express)."""

    x: np.ndarray
    y: np.ndarray
    reward: np.ndarray
    agg_score: np.ndarray = None
    meta: dict = field(default_factory=dict)

    def __len__(self) -> int:
        return self.x.shape[0]


def iter_trace_lines(path: str) -> Iterator[dict]:
    """Lazily parse one export file; malformed lines (a torn tail from a
    live scheduler, a rotation boundary) are skipped, not fatal."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue


def apply_wal_record(rec: dict, evicted: set, node_domain: dict) -> None:
    """Fold ONE parsed WAL record into the outcome maps: a bound pod's
    DELETE is the eviction/preemption signal (victims are deleted by
    the scheduler's eviction flush; a completed pod exits through the
    same door — both mean the placement did not stick), and node
    ADD/UPDATE events carry the labels that map each node to its zone
    (hostname fallback) domain. Idempotent (sets/last-wins), so the
    learn-loop's incremental WAL tail can safely re-apply a window."""
    from kubernetes_tpu.utils.wire import from_wire

    kind = rec.get("kind")
    try:
        if kind == "pods" and rec.get("type") == "delete":
            old = from_wire(rec.get("old"))
            if old is not None and old.spec.node_name:
                evicted.add(old.metadata.uid)
        elif kind == "nodes" and rec.get("type") in ("add", "update"):
            new = from_wire(rec.get("new"))
            if new is not None:
                labels = new.metadata.labels or {}
                node_domain[new.metadata.name] = labels.get(
                    ZONE_LABEL,
                    labels.get(HOSTNAME_LABEL, new.metadata.name))
    except Exception:  # noqa: BLE001 — one bad record is data loss,
        pass           # not a failed build


def wal_outcomes(wal_path: str) -> tuple[set, dict]:
    """(evicted_uids, node -> topology domain) from the whole journal
    WAL (apply_wal_record over every line)."""
    evicted: set = set()
    node_domain: dict = {}
    with open(wal_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue        # torn tail — storage tolerates it too
            apply_wal_record(rec, evicted, node_domain)
    return evicted, node_domain


def iter_placement_rows(lines: Iterable[dict]) -> Iterator[dict]:
    """Flatten trace lines into per-placement row dicts — {"uid",
    "node", "score", "feat", "alt", "t"} with node None for failed
    attempts (time-to-bind anchors). Pre-v2 lines yield nothing. The
    shared substrate of the file-based builder, the learn-loop's
    in-memory tail, and regret computation."""
    for line in lines:
        if not isinstance(line, dict) \
                or line.get("v", 1) < REPLAY_MIN_VERSION:
            continue
        t = float(line.get("start", 0.0))
        for row in line.get("placements") or []:
            yield {"uid": row.get("uid", ""), "node": row.get("node"),
                   "score": float(row.get("score", 0.0)),
                   "feat": row.get("feat"),
                   "alt": row.get("alt"), "t": t}


def build_dataset_rows(rows: Iterable[dict],
                       evicted: Optional[set] = None,
                       node_domain: Optional[dict] = None,
                       max_examples: int = 500_000) -> ReplayDataset:
    """The dataset arithmetic over flattened placement rows
    (iter_placement_rows shape): BC targets from the feature rows,
    outcome rewards shaded by time-to-bind, evictions, and domain
    crowding. Raises ValueError when no row carries a feature vector."""
    feats: list = []
    scores: list = []
    uids: list = []
    nodes: list = []
    first_seen: dict = {}
    bind_at: dict = {}
    rows_seen = 0
    for row in rows:
        rows_seen += 1
        uid = row.get("uid", "")
        t = float(row.get("t", 0.0))
        if uid and uid not in first_seen:
            first_seen[uid] = t
        node = row.get("node")
        if node is None:
            continue        # failed attempt: time-to-bind anchor only
        feat = row.get("feat")
        if not feat or len(feat) != NUM_FEATURES:
            continue
        if len(feats) >= max_examples:
            continue
        bind_at.setdefault(uid, t)
        feats.append(feat)
        scores.append(float(row.get("score", 0.0)))
        uids.append(uid)
        nodes.append(node)
    if not feats:
        raise ValueError(
            f"no placement rows with feature vectors among {rows_seen} "
            "rows; run the scheduler with trace_export_path set AND "
            "trace_export_features=true (the feature export is opt-in)")
    x = np.asarray(feats, np.float32)
    y = bc_targets(x)
    reward = np.ones((len(feats),), np.float32)

    # time-to-bind shading: placements that took longer than the median
    # pod (first attempt -> bind) carry less weight
    ttbs = {u: bind_at[u] - first_seen.get(u, bind_at[u]) for u in bind_at}
    med = float(np.median(list(ttbs.values()))) if ttbs else 0.0
    if med > 0:
        for i, uid in enumerate(uids):
            rel = ttbs.get(uid, med) / med
            reward[i] /= 1.0 + max(0.0, rel - 1.0) * SLOW_BIND_SHADE

    evicted = evicted or set()
    node_domain = node_domain or {}
    for i, uid in enumerate(uids):
        if uid in evicted:
            reward[i] *= EVICT_PENALTY
    # topology-domain crowding: placements into domains that ended up
    # holding more than their share of this replay's pods shade down —
    # the spread-imbalance outcome label
    domains = [node_domain.get(n, n) for n in nodes]
    counts: dict = {}
    for d in domains:
        counts[d] = counts.get(d, 0) + 1
    if len(counts) > 1:
        mean = sum(counts.values()) / len(counts)
        for i, d in enumerate(domains):
            imb = counts[d] / mean
            reward[i] /= 1.0 + max(0.0, imb - 1.0) * CROWDING_SHADE
    return ReplayDataset(
        x=x, y=y, reward=reward,
        agg_score=np.asarray(scores, np.float32),
        meta={"examples": len(feats),
              "evicted": len(evicted),
              "domains": len(counts),
              "uids": uids, "nodes": nodes,
              "ttb_median_s": round(med, 6)})


def build_dataset(trace_paths: Iterable[str],
                  wal_path: Optional[str] = None,
                  max_examples: int = 500_000) -> ReplayDataset:
    """Reconstruct a training set from export files (+ optional WAL for
    outcome labels). Raises ValueError when no usable placement rows are
    found (exports predating format v2 carry no feature rows)."""
    lines = 0
    skipped_old = 0
    raw: list = []
    for path in ([trace_paths] if isinstance(trace_paths, str)
                 else list(trace_paths)):
        for line in iter_trace_lines(path):
            lines += 1
            if line.get("v", 1) < REPLAY_MIN_VERSION:
                skipped_old += 1
                continue
            raw.append(line)
    evicted: set = set()
    node_domain: dict = {}
    if wal_path:
        evicted, node_domain = wal_outcomes(wal_path)
    try:
        ds = build_dataset_rows(iter_placement_rows(raw),
                                evicted=evicted, node_domain=node_domain,
                                max_examples=max_examples)
    except ValueError:
        raise ValueError(
            f"no v{REPLAY_MIN_VERSION}+ placement rows with feature "
            f"vectors found ({lines} trace lines, {skipped_old} "
            f"pre-v{REPLAY_MIN_VERSION}); run the scheduler with "
            "trace_export_path set AND trace_export_features=true "
            "(the feature export is opt-in)") from None
    ds.meta.pop("uids", None)
    ds.meta.pop("nodes", None)
    ds.meta.update({"trace_lines": lines, "skipped_pre_v2": skipped_old})
    return ds


def synthetic_dataset(seed: int = 0, n: int = 512,
                      noise: float = 2.0) -> ReplayDataset:
    """A tiny synthetic replay for smoke training (CI keeps a <30s
    train on this): features uniform in the unit cube, targets the
    hand-tuned-shaped combination plus noise, rewards favoring
    low-utilization placements (a learnable signal distinct from the
    BC target)."""
    rng = np.random.default_rng(seed)
    x = rng.uniform(0.0, 1.0, size=(n, NUM_FEATURES)).astype(np.float32)
    y = np.clip(bc_targets(x) + rng.normal(0.0, noise, size=n),
                0.0, 100.0).astype(np.float32)
    reward = (1.25 - 0.5 * (x[:, 0] + x[:, 1]) / 2.0).astype(np.float32)
    return ReplayDataset(x=x, y=y, reward=reward,
                         meta={"examples": n, "synthetic": True,
                               "seed": seed})
