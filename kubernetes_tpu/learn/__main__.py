"""Learned-scorer CLI: ``python -m kubernetes_tpu.learn <cmd>``.

    train     build a replay dataset from trace exports (+ optional WAL)
              — or --synthetic N — and train a checkpoint
    loop      the retrain daemon (learn/loop.py): tail the rotating
              trace export, retrain on a cadence, gate candidates
              against the live checkpoint on held-out rows, promote
              winners to the path the scheduler hot-reloads (--once
              runs one iteration and prints the report)
    identity  write the identity-init checkpoint (reproduces the
              hand-tuned aggregate; the differential-test fixture)
    inspect   print a checkpoint's meta + shape chain
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubernetes-tpu-learn")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_train = sub.add_parser("train", help="train a scorer checkpoint")
    p_train.add_argument("--traces", nargs="*", default=[],
                         help="flight-recorder JSON-lines export files "
                              "(scheduler --trace-export)")
    p_train.add_argument("--wal", default=None,
                         help="hub journal WAL for outcome labels")
    p_train.add_argument("--synthetic", type=int, default=0,
                         help="train on N synthetic examples instead of "
                              "trace exports (smoke/CI)")
    p_train.add_argument("--out", required=True, help="checkpoint path")
    p_train.add_argument("--seed", type=int, default=0)
    p_train.add_argument("--hidden", type=int, nargs="*", default=[8])
    p_train.add_argument("--bc-epochs", type=int, default=300)
    p_train.add_argument("--ft-epochs", type=int, default=150)
    p_train.add_argument("--version", type=int, default=None,
                         help="checkpoint version stamp (monotonic per "
                              "deployment; surfaced by the "
                              "scheduler_learned_checkpoint_version "
                              "gauge). Default: one past the version "
                              "already at --out, so a forgotten flag "
                              "never walks the gauge backwards")

    p_loop = sub.add_parser(
        "loop", help="retrain daemon: tail exports, retrain, gate, "
                     "promote (learn/loop.py)")
    p_loop.add_argument("--traces", required=True,
                        help="the scheduler's ROTATING trace export "
                             "path (the .1 rotation sibling is tailed "
                             "automatically)")
    p_loop.add_argument("--wal", default=None,
                        help="hub journal WAL for outcome labels")
    p_loop.add_argument("--staging", required=True,
                        help="staging dir: candidates, last-good, "
                             "cursor/loop state")
    p_loop.add_argument("--live", required=True,
                        help="the LIVE checkpoint path the scheduler's "
                             "CheckpointWatcher polls — only gated "
                             "winners land here")
    p_loop.add_argument("--once", action="store_true",
                        help="run one loop body and exit (the "
                             "one-command closed-loop proof)")
    p_loop.add_argument("--interval", type=float, default=300.0)
    p_loop.add_argument("--min-rows", type=int, default=64)
    p_loop.add_argument("--seed", type=int, default=0)
    p_loop.add_argument("--hidden", type=int, nargs="*", default=[8])
    p_loop.add_argument("--bc-epochs", type=int, default=120)
    p_loop.add_argument("--ft-epochs", type=int, default=60)

    p_id = sub.add_parser("identity", help="identity-init checkpoint")
    p_id.add_argument("--out", required=True)
    # version 0 is the checkpoint-version gauge's "none loaded"
    # sentinel; a deployed identity checkpoint must read as loaded
    p_id.add_argument("--version", type=int, default=1)

    p_ins = sub.add_parser("inspect", help="print checkpoint meta")
    p_ins.add_argument("path")

    args = parser.parse_args(argv)

    from kubernetes_tpu.learn import checkpoint as ck

    if args.cmd == "inspect":
        params, meta = ck.load_checkpoint(args.path)
        print(json.dumps({
            "meta": meta,
            "layers": [{"w": list(w.shape), "b": list(b.shape)}
                       for w, b in params],
        }, indent=2, default=str))
        return 0

    if args.cmd == "identity":
        from kubernetes_tpu.learn.train import identity_params

        doc = ck.save_checkpoint(args.out, identity_params(),
                                 meta={"identity": True,
                                       "version": args.version})
        print(json.dumps({"written": args.out, "meta": doc["meta"]}))
        return 0

    if args.cmd == "loop":
        from kubernetes_tpu.learn.loop import LearnLoop, LoopConfig

        loop = LearnLoop(LoopConfig(
            trace_path=args.traces, wal_path=args.wal,
            staging_dir=args.staging, live_path=args.live,
            interval_s=args.interval, min_new_rows=args.min_rows,
            seed=args.seed, hidden=tuple(args.hidden),
            bc_epochs=args.bc_epochs, ft_epochs=args.ft_epochs))
        if args.once:
            report = loop.run_once()
            print(json.dumps(report, default=str))
            return 0
        loop.run_forever()
        return 0

    # train
    from kubernetes_tpu.learn.replay import build_dataset, synthetic_dataset
    from kubernetes_tpu.learn.train import TrainConfig, train

    if args.synthetic:
        ds = synthetic_dataset(seed=args.seed, n=args.synthetic)
    elif args.traces:
        ds = build_dataset(args.traces, wal_path=args.wal)
    else:
        print("train needs --traces or --synthetic", file=sys.stderr)
        return 2
    # auto-bump: an unset --version continues the existing checkpoint's
    # sequence instead of republishing version 1 over it
    version = (args.version if args.version is not None
               else ck.next_version(args.out))
    cfg = TrainConfig(hidden=tuple(args.hidden), seed=args.seed,
                      bc_epochs=args.bc_epochs, ft_epochs=args.ft_epochs,
                      meta={"version": version, **ds.meta})
    params, info = train(ds, cfg)
    doc = ck.save_checkpoint(args.out, params, meta=info)
    print(json.dumps({"written": args.out, "meta": doc["meta"]},
                     default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
