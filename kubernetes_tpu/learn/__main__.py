"""Learned-scorer CLI: ``python -m kubernetes_tpu.learn <cmd>``.

    train     build a replay dataset from trace exports (+ optional WAL)
              — or --synthetic N — and train a checkpoint
    identity  write the identity-init checkpoint (reproduces the
              hand-tuned aggregate; the differential-test fixture)
    inspect   print a checkpoint's meta + shape chain
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubernetes-tpu-learn")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_train = sub.add_parser("train", help="train a scorer checkpoint")
    p_train.add_argument("--traces", nargs="*", default=[],
                         help="flight-recorder JSON-lines export files "
                              "(scheduler --trace-export)")
    p_train.add_argument("--wal", default=None,
                         help="hub journal WAL for outcome labels")
    p_train.add_argument("--synthetic", type=int, default=0,
                         help="train on N synthetic examples instead of "
                              "trace exports (smoke/CI)")
    p_train.add_argument("--out", required=True, help="checkpoint path")
    p_train.add_argument("--seed", type=int, default=0)
    p_train.add_argument("--hidden", type=int, nargs="*", default=[8])
    p_train.add_argument("--bc-epochs", type=int, default=300)
    p_train.add_argument("--ft-epochs", type=int, default=150)
    p_train.add_argument("--version", type=int, default=1,
                         help="checkpoint version stamp (monotonic per "
                              "deployment; surfaced by the "
                              "scheduler_learned_checkpoint_version gauge)")

    p_id = sub.add_parser("identity", help="identity-init checkpoint")
    p_id.add_argument("--out", required=True)
    # version 0 is the checkpoint-version gauge's "none loaded"
    # sentinel; a deployed identity checkpoint must read as loaded
    p_id.add_argument("--version", type=int, default=1)

    p_ins = sub.add_parser("inspect", help="print checkpoint meta")
    p_ins.add_argument("path")

    args = parser.parse_args(argv)

    from kubernetes_tpu.learn import checkpoint as ck

    if args.cmd == "inspect":
        params, meta = ck.load_checkpoint(args.path)
        print(json.dumps({
            "meta": meta,
            "layers": [{"w": list(w.shape), "b": list(b.shape)}
                       for w, b in params],
        }, indent=2, default=str))
        return 0

    if args.cmd == "identity":
        from kubernetes_tpu.learn.train import identity_params

        doc = ck.save_checkpoint(args.out, identity_params(),
                                 meta={"identity": True,
                                       "version": args.version})
        print(json.dumps({"written": args.out, "meta": doc["meta"]}))
        return 0

    # train
    from kubernetes_tpu.learn.replay import build_dataset, synthetic_dataset
    from kubernetes_tpu.learn.train import TrainConfig, train

    if args.synthetic:
        ds = synthetic_dataset(seed=args.seed, n=args.synthetic)
    elif args.traces:
        ds = build_dataset(args.traces, wal_path=args.wal)
    else:
        print("train needs --traces or --synthetic", file=sys.stderr)
        return 2
    cfg = TrainConfig(hidden=tuple(args.hidden), seed=args.seed,
                      bc_epochs=args.bc_epochs, ft_epochs=args.ft_epochs,
                      meta={"version": args.version, **ds.meta})
    params, info = train(ds, cfg)
    doc = ck.save_checkpoint(args.out, params, meta=info)
    print(json.dumps({"written": args.out, "meta": doc["meta"]},
                     default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
