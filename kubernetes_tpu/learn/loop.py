"""The retrain daemon: tail exports → retrain → gate → promote.

Closes the learning loop PR 8 left open (ROADMAP item 4; the RL
custom-scheduler's online policy tuning, arXiv:2601.13579, and
"Learning to Score"'s reward-driven refresh, arXiv:2603.10545): instead
of a human running ``learn train`` and a new checkpoint going live on
mtime alone,

1. **ExportCursor** tails the scheduler's rotating trace export
   (``path`` + the keep-last-1 ``path.1``) with torn-line- and
   rotation-aware byte cursors: a partial tail line is never consumed
   (the live scheduler is still writing it), a rotation is detected by
   inode and the rotated file's remainder is drained before the fresh
   file, and the cursor persists to the loop state file so a daemon
   restart resumes mid-tail without re-training on duplicate rows.
2. **LearnLoop.run_once** retrains when enough new placement rows
   accumulated: BC warm start, then the regret-weighted
   contextual-bandit fine-tune — each example's outcome reward is
   additionally shaded by its per-placement regret (the export v3
   counterfactual rows), so placements a runner-up would have beaten
   push the scorer hardest. Candidates land in a STAGING path with a
   monotonically-versioned, generation-stamped meta.
3. **Gated promotion**: the candidate is replay-scored against the
   live checkpoint on held-out recent rows (learn.regret.gate_candidate
   — ≥2 quality-metric wins at latency parity) and only a winner is
   published to the path the scheduler's CheckpointWatcher polls.
   The displaced live checkpoint is preserved as ``last-good.json``;
   when the regret observed on traffic scheduled AFTER a promotion
   regresses past the promotion-time baseline, the loop automatically
   republishes last-good (with a fresh version bump so the watcher
   reloads) and counts a rollback.

``python -m kubernetes_tpu.learn loop --once`` runs one iteration and
prints the report; without ``--once`` it polls on a cadence. The
loop's own Registry carries the ``scheduler_learn_loop_*`` metrics.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Optional

from kubernetes_tpu.learn import checkpoint as ck
from kubernetes_tpu.learn import regret as RG
from kubernetes_tpu.learn.replay import (
    apply_wal_record,
    build_dataset_rows,
    iter_placement_rows,
)
from kubernetes_tpu.metrics import Counter, Gauge, Registry
from kubernetes_tpu.ops.learned import MAX_SCORE, NUM_FEATURES

logger = logging.getLogger("kubernetes_tpu.learn.loop")


class LoopMetrics:
    """scheduler_learn_loop_*: the daemon's own registry (it is its own
    process — scraping rides render_text / the report JSON)."""

    def __init__(self, registry: Optional[Registry] = None):
        r = self.registry = registry or Registry()
        self.rows = r.register(Counter(
            "scheduler_learn_loop_rows_total",
            "Placement rows consumed from the trace-export tail"))
        self.retrains = r.register(Counter(
            "scheduler_learn_loop_retrains_total",
            "Retrain rounds completed (a candidate was produced)"))
        self.promotions = r.register(Counter(
            "scheduler_learn_loop_promotions_total",
            "Candidate checkpoints promoted to the live path"))
        self.rejected = r.register(Counter(
            "scheduler_learn_loop_rejected_total",
            "Candidate checkpoints rejected by the promotion gate "
            "(last-good keeps serving)"))
        self.rollbacks = r.register(Counter(
            "scheduler_learn_loop_rollbacks_total",
            "Automatic rollbacks to last-good after a post-promotion "
            "regret regression"))
        self.generation = r.register(Gauge(
            "scheduler_learn_loop_generation",
            "Latest candidate generation this loop produced"))
        self.live_generation = r.register(Gauge(
            "scheduler_learn_loop_live_generation",
            "Generation currently published to the live path"))
        self.regret_mean = r.register(Gauge(
            "scheduler_learn_loop_regret_mean",
            "Mean per-placement regret over the latest consumed rows"))
        self.regret_p99 = r.register(Gauge(
            "scheduler_learn_loop_regret_p99",
            "p99 per-placement regret over the latest consumed rows"))


def _read_complete_lines(fn: str, offset: int,
                         out: list[str]) -> int:
    """Append the COMPLETE lines of ``fn`` after byte ``offset`` to
    ``out``; returns the new offset (never past the last newline, so a
    torn tail a live writer is still producing stays unconsumed). The
    one tail-read primitive both the export cursor and the WAL tail
    build on."""
    try:
        with open(fn, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return offset
    end = data.rfind(b"\n")
    if end < 0:
        return offset
    for raw in data[:end].split(b"\n"):
        if raw.strip():
            out.append(raw.decode("utf-8", "replace"))
    return offset + end + 1


class ExportCursor:
    """Byte cursor over the rotating trace export. ``read_lines``
    returns only COMPLETE new lines (a torn tail stays unconsumed for
    the next poll); rotation (FlightRecorder's keep-last-1
    ``os.replace`` to ``path.1``) is detected by inode, and the rotated
    file's remainder is drained before the fresh file. ``state()`` /
    ``restore()`` round-trip through the loop state file."""

    def __init__(self, path: str):
        self.path = path
        self.ino: Optional[int] = None
        self.offset = 0
        # the rotated predecessor (<path>.1), tracked by its OWN
        # inode+offset so polls while the live file is absent (daemon
        # started first, or a failed rotation disabled the export)
        # never re-consume it from byte 0
        self.prev_ino: Optional[int] = None
        self.prev_offset = 0
        self.lines_read = 0
        # rotations whose predecessor was already replaced again before
        # we polled — those rows are gone (poll faster or raise the
        # export's size bound)
        self.missed_rotations = 0

    def state(self) -> dict:
        return {"ino": self.ino, "offset": self.offset,
                "prev_ino": self.prev_ino,
                "prev_offset": self.prev_offset,
                "lines_read": self.lines_read,
                "missed_rotations": self.missed_rotations}

    def restore(self, st: dict) -> None:
        self.ino = st.get("ino")
        self.offset = int(st.get("offset", 0))
        self.prev_ino = st.get("prev_ino")
        self.prev_offset = int(st.get("prev_offset", 0))
        self.lines_read = int(st.get("lines_read", 0))
        self.missed_rotations = int(st.get("missed_rotations", 0))

    def _consume(self, fn: str, offset: int, out: list[str]) -> int:
        return _read_complete_lines(fn, offset, out)

    def _drain_prev(self, out: list[str]) -> None:
        """Incrementally consume <path>.1 under its own cursor: a fresh
        inode (first sight, or a newer rotation) starts from 0; an
        already-tracked one resumes from prev_offset — repeated polls
        while the live file is absent never duplicate."""
        try:
            st1 = os.stat(self.path + ".1")
        except OSError:
            return
        if st1.st_ino != self.prev_ino:
            self.prev_ino = st1.st_ino
            self.prev_offset = 0
        self.prev_offset = self._consume(self.path + ".1",
                                         self.prev_offset, out)

    def read_lines(self) -> list[str]:
        out: list[str] = []
        try:
            st = os.stat(self.path)
        except OSError:
            st = None
        if self.ino is not None \
                and (st is None or st.st_ino != self.ino):
            # rotation (or the export vanished): our live file should
            # now be path.1 (os.replace keeps the inode) — hand our
            # offset to the predecessor cursor so its tail drains
            try:
                st1 = os.stat(self.path + ".1")
            except OSError:
                st1 = None
            if st1 is not None and st1.st_ino == self.ino:
                self.prev_ino = self.ino
                self.prev_offset = self.offset
            else:
                self.missed_rotations += 1
                logger.warning("export cursor lost a rotation of %s "
                               "(predecessor already replaced)",
                               self.path)
            self.ino = None
            self.offset = 0
        if self.ino is None:
            # (re)attach: drain the rotated predecessor first (oldest
            # rows), then the live file from byte 0
            self._drain_prev(out)
            if st is not None:
                self.ino = st.st_ino
                self.offset = self._consume(self.path, 0, out)
        else:
            # common case: same file, tail from our offset. A file
            # that SHRANK in place (same inode — an operator's
            # `> traces.jsonl`, run_one's warm-pass truncate) restarts
            # from 0 like WalTail: seeking past EOF would silently
            # skip everything written until the file regrows
            if st.st_size < self.offset:
                self.offset = 0
            self.offset = self._consume(self.path, self.offset, out)
        self.lines_read += len(out)
        return out


class WalTail:
    """Incremental outcome harvest over the hub journal WAL: each poll
    parses only the bytes appended since the last one (a daemon body
    must stay O(new events), not O(total WAL size)) and folds them
    into cumulative evicted/node_domain maps. A WAL that SHRANK (boot
    compaction rewrote it) re-reads from 0 — apply_wal_record is
    idempotent, so re-applying a window is merge-safe. Only the
    JSON-lines WAL codec is readable here: a bin1 WAL (the fabric
    default) is detected by its first byte and DISABLES the tail with
    a loud error instead of silently yielding no outcome labels (and
    re-reading binary bytes forever)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.offset = 0
        self.evicted: set = set()
        self.node_domain: dict = {}
        self.disabled = False

    def _sniff(self) -> bool:
        """True when the WAL head looks like JSON lines; a binary head
        (bin1 length-prefixed frames) disables the tail loudly."""
        try:
            with open(self.path, "rb") as f:
                head = f.read(1)
        except OSError:
            return True              # not readable yet — try later
        if not head or head in b"{ \t\n\r":
            return True
        self.disabled = True
        logger.error(
            "WAL %s is not a JSON-lines WAL (first byte %r — a bin1 "
            "fabric WAL?); outcome labels DISABLED. Point --wal at a "
            "wal_codec=json hub WAL, or run without outcome labels.",
            self.path, head)
        return False

    def outcomes(self) -> tuple[set, dict]:
        if not self.path or self.disabled:
            return self.evicted, self.node_domain
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return self.evicted, self.node_domain
        if size < self.offset:
            self.offset = 0          # compacted/rewritten: re-merge
        if size == self.offset or not self._sniff():
            return self.evicted, self.node_domain
        lines: list[str] = []
        self.offset = _read_complete_lines(self.path, self.offset,
                                           lines)
        for ln in lines:
            try:
                rec = json.loads(ln)
            except ValueError:
                continue             # torn record — storage tolerates it
            apply_wal_record(rec, self.evicted, self.node_domain)
        return self.evicted, self.node_domain


@dataclass
class LoopConfig:
    trace_path: str                  # the scheduler's rotating export
    staging_dir: str                 # candidates + last-good + state
    live_path: str                   # what CheckpointWatcher polls
    wal_path: Optional[str] = None   # hub journal WAL (outcome labels)
    state_path: Optional[str] = None  # default: <staging>/loop_state.json
    interval_s: float = 300.0
    min_new_rows: int = 64           # trainable rows before a retrain
    holdout_frac: float = 0.3        # newest rows held out for the gate
    min_holdout_rows: int = 8
    max_buffer_rows: int = 200_000
    seed: int = 0
    hidden: tuple = (8,)
    bc_epochs: int = 120
    ft_epochs: int = 60
    # extra reward shading per unit of normalized regret (the
    # contextual-bandit term: high-regret placements push hardest)
    regret_gain: float = 1.0
    quality_eps: float = 0.01
    latency_budget: float = 0.5
    # post-promotion regret regression that triggers rollback, relative
    # to the promotion-time baseline (plus a small absolute floor so a
    # near-zero baseline doesn't roll back on noise)
    rollback_tolerance: float = 0.25
    rollback_floor: float = 0.5
    min_rollback_rows: int = 16

    def resolved_state_path(self) -> str:
        return self.state_path or os.path.join(self.staging_dir,
                                               "loop_state.json")


class LearnLoop:
    """One retrain daemon instance. ``run_once`` is the whole loop body
    (tail → rollback check → retrain → gate → promote); ``run_forever``
    sleeps ``interval_s`` between bodies."""

    def __init__(self, cfg: LoopConfig,
                 metrics: Optional[LoopMetrics] = None,
                 now=time.time):
        self.cfg = cfg
        self.metrics = metrics or LoopMetrics()
        self.now = now
        os.makedirs(cfg.staging_dir, exist_ok=True)
        self.cursor = ExportCursor(cfg.trace_path)
        self.wal = WalTail(cfg.wal_path)
        self.state = {"generation": 0, "version": 0, "promoted": None}
        self._load_state()
        # the row buffer SPOOLS to staging: the cursor advances past
        # consumed rows immediately, so a sub-threshold window read by
        # a one-shot `--once` invocation (a fresh process every
        # interval) must survive to the next invocation or those rows
        # are unreachable forever and a low-rate deployment never
        # accumulates to min_new_rows
        self._buffer_path = os.path.join(cfg.staging_dir,
                                         "row_buffer.jsonl")
        self._buffer: list[dict] = self._load_buffer()
        # trainable rows since the last retrain (persisted with the
        # state for the same one-shot reason)
        self._pending = int(self.state.pop("pending", 0))

    # ------------------------------------------------------- state ---

    def _load_state(self) -> None:
        try:
            with open(self.cfg.resolved_state_path()) as f:
                st = json.load(f)
        except (OSError, ValueError):
            return
        self.cursor.restore(st.get("cursor") or {})
        for k in ("generation", "version", "promoted", "pending"):
            if k in st:
                self.state[k] = st[k]

    def _save_state(self) -> None:
        path = self.cfg.resolved_state_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"cursor": self.cursor.state(),
                       "pending": self._pending, **self.state}, f)
        os.replace(tmp, path)

    def _load_buffer(self) -> list[dict]:
        rows: list[dict] = []
        try:
            with open(self._buffer_path) as f:
                for line in f:
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue     # torn tail from a killed writer
        except OSError:
            return []
        return rows[-self.cfg.max_buffer_rows:]

    def _extend_buffer(self, new_rows: list[dict]) -> None:
        """Append to the in-memory buffer AND its on-disk spool;
        an over-bound buffer trims to the newest window (the spool is
        rewritten atomically so a crash never tears it)."""
        if new_rows:
            self._buffer.extend(new_rows)
            try:
                with open(self._buffer_path, "a") as f:
                    for r in new_rows:
                        f.write(json.dumps(r) + "\n")
            except OSError:
                logger.warning("row-buffer spool append failed; "
                               "one-shot restarts may lose this window",
                               exc_info=True)
        if len(self._buffer) > self.cfg.max_buffer_rows:
            self._buffer = self._buffer[-self.cfg.max_buffer_rows:]
            try:
                tmp = f"{self._buffer_path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    for r in self._buffer:
                        f.write(json.dumps(r) + "\n")
                os.replace(tmp, self._buffer_path)
            except OSError:
                logger.warning("row-buffer spool trim failed",
                               exc_info=True)

    def _last_good_path(self) -> str:
        return os.path.join(self.cfg.staging_dir, "last-good.json")

    def _next_version(self) -> int:
        """Monotonic across restarts AND manual publishes: one past the
        max of our own state and whatever currently serves live
        (ck.next_version reads the live checkpoint's sequence)."""
        return max(int(self.state.get("version", 0)) + 1,
                   ck.next_version(self.cfg.live_path))

    # ---------------------------------------------------- rollback ---

    def _check_rollback(self, regret_summary: dict) -> Optional[dict]:
        """Post-promotion watch: regret observed on rows scheduled
        UNDER the promoted generation regressing past the promotion
        baseline republishes last-good. Evidence ACCUMULATES across
        polls (persisted with the state) so low-rate traffic — a few
        placements per interval — still reaches the min_rollback_rows
        bar instead of resetting every body."""
        promoted = self.state.get("promoted")
        if not promoted:
            return None
        n = int(regret_summary.get("count", 0))
        if n:
            promoted["observed_count"] = \
                promoted.get("observed_count", 0) + n
            promoted["observed_sum"] = (
                promoted.get("observed_sum", 0.0)
                + float(regret_summary.get("regret_mean", 0.0)) * n)
        total = int(promoted.get("observed_count", 0))
        if total < self.cfg.min_rollback_rows:
            return None
        baseline = float(promoted.get("regret_mean", 0.0))
        observed = promoted["observed_sum"] / total
        bar = (baseline * (1.0 + self.cfg.rollback_tolerance)
               + self.cfg.rollback_floor)
        if observed <= bar:
            return None
        try:
            params, meta = ck.load_checkpoint(self._last_good_path())
        except ck.CheckpointError as e:
            # no recovery path exists — disarm the watch (logging the
            # same unusable-last-good error every poll forever helps
            # nobody); the next successful retrain takes over
            logger.error("regret regressed (%.3f > %.3f) but last-good "
                         "is unusable; disarming the rollback watch: "
                         "%s", observed, bar, e)
            self.state["promoted"] = None
            return None
        version = self._next_version()
        clean = {k: v for k, v in meta.items()
                 if k not in ("format_version", "feature_version",
                              "fingerprint", "created")}
        clean.update(version=version,
                     rolled_back_from=promoted.get("generation"),
                     rollback_observed_regret=observed,
                     rollback_baseline_regret=baseline)
        ck.save_checkpoint(self.cfg.live_path, params, meta=clean)
        self.state["version"] = version
        self.state["promoted"] = None
        self.metrics.rollbacks.inc()
        self.metrics.live_generation.set(
            float(clean.get("generation", 0)))
        logger.warning("rolled back to last-good (generation %s, "
                       "version %s): observed regret %.3f > %.3f",
                       clean.get("generation"), version, observed, bar)
        return {"rolled_back_to": clean.get("generation"),
                "version": version, "observed": observed,
                "baseline": baseline}

    # ---------------------------------------------------- one body ---

    def run_once(self) -> dict:
        cfg = self.cfg
        lines = self.cursor.read_lines()
        parsed = []
        for ln in lines:
            try:
                parsed.append(json.loads(ln))
            except ValueError:
                continue        # torn/garbled line — skip, not fatal
        new_rows = list(iter_placement_rows(parsed))
        self.metrics.rows.inc(len(new_rows))
        self._extend_buffer(new_rows)
        trainable = sum(1 for r in new_rows
                        if r.get("node") is not None and r.get("feat")
                        and len(r["feat"]) == NUM_FEATURES)
        self._pending += trainable

        evicted, node_domain = self.wal.outcomes()
        new_regret = RG.summarize_regret(
            RG.compute_regret(new_rows, evicted, node_domain))
        if new_regret["count"]:
            self.metrics.regret_mean.set(new_regret["regret_mean"])
            self.metrics.regret_p99.set(new_regret["regret_p99"])

        report = {"at": self.now(), "new_rows": len(new_rows),
                  "new_trainable": trainable,
                  "pending": self._pending,
                  "buffer": len(self._buffer),
                  "regret": new_regret,
                  "cursor": self.cursor.state()}

        # the promoted generation is judged on the traffic it scheduled
        rb = self._check_rollback(new_regret)
        if rb:
            report["rollback"] = rb

        if self._pending < cfg.min_new_rows:
            report["status"] = "waiting"
            self._save_state()
            return report

        # ----- split: newest rows held out for the gate -----
        rows = sorted(self._buffer, key=lambda r: r.get("t", 0.0))
        usable = [r for r in rows
                  if r.get("node") is not None and r.get("feat")
                  and len(r["feat"]) == NUM_FEATURES]
        n_hold = max(cfg.min_holdout_rows,
                     int(len(usable) * cfg.holdout_frac))
        if len(usable) < n_hold + cfg.min_holdout_rows:
            # min_holdout_rows is a FLOOR on the gate's evidence, not a
            # budget to steal from training: too few rows for a real
            # holdout + train split means keep accumulating
            report["status"] = "waiting"
            report["reason"] = "insufficient rows for holdout split"
            self._save_state()
            return report
        holdout = usable[-n_hold:]
        cut_t = holdout[0].get("t", 0.0)
        train_rows = [r for r in rows if r.get("t", 0.0) < cut_t] \
            or usable[:-n_hold] or usable
        # the gate's time-to-bind axis needs the failed-attempt anchor
        # rows (node None) of the held-out pods — they establish
        # first_seen; without them every time-to-bind collapses to 0
        holdout_uids = {r.get("uid", "") for r in holdout}
        gate_rows = holdout + [
            r for r in rows
            if r.get("node") is None and r.get("uid") in holdout_uids]

        # ----- retrain: BC warm start + regret-weighted bandit FT -----
        from kubernetes_tpu.learn.train import TrainConfig, train

        generation = int(self.state.get("generation", 0)) + 1
        version = self._next_version()
        try:
            ds = build_dataset_rows(train_rows, evicted=evicted,
                                    node_domain=node_domain)
        except ValueError as e:
            report["status"] = "no_trainable_rows"
            report["error"] = str(e)
            self._save_state()
            return report
        # contextual-bandit shading: fold each example's per-placement
        # regret (normalized to score scale) into its outcome reward so
        # the fine-tune's advantage pushes hardest where a counterfactual
        # alternative was measurably better
        train_regret = RG.compute_regret(train_rows, evicted, node_domain)
        reg_by_uid: dict = {}
        for rec in train_regret:
            reg_by_uid[rec["uid"]] = rec["regret"]
        uids = ds.meta.get("uids") or []
        for i, uid in enumerate(uids):
            reg = reg_by_uid.get(uid, 0.0)
            if reg > 0:
                ds.reward[i] /= (1.0
                                 + (reg / MAX_SCORE) * cfg.regret_gain)
        train_summary = RG.summarize_regret(train_regret)
        params, info = train(ds, TrainConfig(
            hidden=tuple(cfg.hidden), seed=cfg.seed + generation,
            bc_epochs=cfg.bc_epochs, ft_epochs=cfg.ft_epochs,
            meta={"version": version, "generation": generation,
                  "source": "learn_loop", "regret": train_summary}))
        cand_path = os.path.join(cfg.staging_dir,
                                 f"scorer-g{generation}.json")
        ck.save_checkpoint(cand_path, params, meta=info)
        self.metrics.retrains.inc()
        self.metrics.generation.set(float(generation))
        self.state["generation"] = generation
        self.state["version"] = version
        report.update(generation=generation, version=version,
                      candidate=cand_path, examples=len(ds),
                      train_regret=train_summary)

        # ----- gate: replay-score candidate vs live on the holdout -----
        live_params = None
        live_meta: dict = {}
        try:
            live_params, live_meta = ck.load_checkpoint(cfg.live_path)
        except ck.CheckpointError:
            pass                      # bootstrap: nothing serving yet
        gate = RG.gate_candidate(
            params, live_params, gate_rows, evicted, node_domain,
            quality_eps=cfg.quality_eps,
            latency_budget=cfg.latency_budget)
        report["gate"] = {k: gate[k] for k in
                          ("promote", "bootstrap", "wins", "losses",
                           "latency_ok")}
        if gate["promote"]:
            if live_params is not None:
                # preserve the displaced live checkpoint for rollback
                clean = {k: v for k, v in live_meta.items()
                         if k not in ("format_version",
                                      "feature_version", "fingerprint",
                                      "created")}
                ck.save_checkpoint(self._last_good_path(), live_params,
                                   meta=clean)
            holdout_regret = RG.summarize_regret(
                RG.compute_regret(gate_rows, evicted, node_domain))
            promote_meta = dict(info)
            promote_meta.update(promoted=True,
                                gate_wins=gate["wins"],
                                holdout_regret=holdout_regret)
            ck.save_checkpoint(cfg.live_path, params, meta=promote_meta)
            self.metrics.promotions.inc()
            self.metrics.live_generation.set(float(generation))
            if live_params is not None:
                # the rollback baseline: regret of the traffic the
                # PREVIOUS policy scheduled — the promoted generation
                # must not do measurably worse than what it replaced.
                # Computed over the FULL row buffer (anchors included)
                # with exactly the methodology _check_rollback applies
                # to new rows, so the comparison is bias-free (anchors
                # drive the time-to-bind shading; stripping them would
                # systematically deflate the baseline and trigger
                # spurious rollbacks)
                baseline = RG.summarize_regret(
                    RG.compute_regret(rows, evicted, node_domain))
                self.state["promoted"] = {
                    "generation": generation, "version": version,
                    "regret_mean": baseline.get("regret_mean", 0.0),
                    "at": self.now()}
            else:
                # bootstrap: nothing was displaced, so there is no
                # last-good to roll back to — arming the watch would
                # only log an unusable-last-good error every poll
                self.state["promoted"] = None
            report["status"] = "promoted"
        else:
            self.metrics.rejected.inc()
            report["status"] = "rejected"
        self._pending = 0
        self._save_state()
        return report

    def run_forever(self, iterations: Optional[int] = None,
                    sleep=time.sleep) -> None:
        n = 0
        while iterations is None or n < iterations:
            try:
                report = self.run_once()
                logger.info("learn loop: %s",
                            json.dumps(report, default=str))
            except Exception:  # noqa: BLE001 — a transient failure
                # (full disk, NFS blip mid-save) must not kill the
                # daemon; the next interval retries from the persisted
                # cursor
                logger.exception("learn loop body failed; retrying "
                                 "next interval")
            n += 1
            if iterations is not None and n >= iterations:
                break
            sleep(self.cfg.interval_s)
