"""Per-placement regret + the promotion gate's replay scorer.

**Regret** (ROADMAP item 4): for each exported placement the export v3
rows carry the top-K alternative node scores the device pipeline
computed in the same launch (``trace_export_alts``). The journal/WAL
outcome labels — evictions (a bound pod's DELETE), slow time-to-bind,
topology-domain crowding — shade the CHOSEN placement's realized value
exactly like the replay dataset's reward shading, and

    regret = max(0, best_alternative_score − chosen_score × outcome)

is the score mass the scheduler gave up by the choice it made, in
aggregate-score points: 0 when the chosen node was best and its
placement stuck, positive when a runner-up would have been better or
the outcome went bad. Summaries (mean/p50/p99) land in every bench
artifact row that ran with the alt export on, in the learn-loop's
metrics, and in the promoted checkpoint's meta (/debug/scorer).

**Replay scoring** (the gate): a candidate checkpoint is compared to
the live one on held-out recent placement rows WITHOUT touching the
cluster — each policy scores the rows it would have preferred, and the
preference mass it concentrates on placements whose measured outcome
was bad on each quality axis is its demerit:

- ``preemptions``   — preference mass on later-evicted placements
- ``spread``        — preference-weighted domain-crowding excess
- ``time_to_bind_p99_s`` — preference-weighted p99 of time-to-bind

Lower is better on all three. ``gate_candidate`` promotes only when
the candidate wins ≥2 metrics (or strictly improves ≥1 with zero
regressions, for near-degenerate clean traffic) at latency parity —
the "Learning to Score" quality bar, evaluated offline so a bad
candidate never serves a single placement.

Everything here is host-side numpy over parsed export rows — no device
work, no JAX import at module load.
"""

from __future__ import annotations

import time
from typing import Iterable, Optional

import numpy as np

from kubernetes_tpu.learn.replay import (
    CROWDING_SHADE,
    EVICT_PENALTY,
    HOSTNAME_LABEL,
    SLOW_BIND_SHADE,
    ZONE_LABEL,
)
from kubernetes_tpu.ops.learned import MAX_SCORE, NUM_FEATURES

# the three gated quality metrics, in reporting order
QUALITY_METRICS = ("preemptions", "spread", "time_to_bind_p99_s")


def np_mlp(params, x: np.ndarray) -> np.ndarray:
    """The ops.learned.mlp_apply forward pass in plain numpy — the gate
    scores thousands of held-out rows without a JAX dispatch (and its
    latency probe measures param-stack cost, not jit cache state)."""
    out = np.asarray(x, np.float32)
    last = len(params) - 1
    for i, (w, b) in enumerate(params):
        out = out @ np.asarray(w, np.float32) + np.asarray(b, np.float32)
        if i < last:
            out = np.maximum(out, 0.0)
    return out[..., 0]


def _ttb_map(rows: list[dict]) -> dict[str, float]:
    """uid -> time-to-bind seconds (first exported attempt -> bind
    cycle), the same anchoring as the replay dataset's shading.
    Order-INDEPENDENT (min over timestamps, not first list occurrence):
    callers assemble row windows out of chronological order — e.g. the
    gate's holdout + appended anchor rows."""
    first_seen: dict[str, float] = {}
    bind_at: dict[str, float] = {}
    for r in rows:
        uid = r.get("uid", "")
        if not uid:
            continue
        t = float(r.get("t", 0.0))
        first_seen[uid] = min(first_seen.get(uid, t), t)
        if r.get("node") is not None:
            bind_at[uid] = min(bind_at.get(uid, t), t)
    return {u: bind_at[u] - first_seen.get(u, bind_at[u]) for u in bind_at}


def _domain_counts(rows: list[dict],
                   node_domain: dict) -> tuple[dict, float]:
    counts: dict = {}
    for r in rows:
        n = r.get("node")
        if n is None:
            continue
        d = node_domain.get(n, n)
        counts[d] = counts.get(d, 0) + 1
    mean = (sum(counts.values()) / len(counts)) if counts else 0.0
    return counts, mean


def outcome_factors(rows: list[dict], evicted: Optional[set] = None,
                    node_domain: Optional[dict] = None) -> list[float]:
    """Per-row realized-outcome factor around 1.0, aligned with
    ``rows`` — the exact shading arithmetic the replay dataset applies
    to rewards (evictions, slow binds, domain crowding), reused so
    regret and training read the same outcome labels."""
    evicted = evicted or set()
    node_domain = node_domain or {}
    ttbs = _ttb_map(rows)
    med = float(np.median(list(ttbs.values()))) if ttbs else 0.0
    counts, mean = _domain_counts(rows, node_domain)
    out = []
    for r in rows:
        f = 1.0
        uid = r.get("uid", "")
        node = r.get("node")
        if node is not None:
            if uid in evicted:
                f *= EVICT_PENALTY
            if med > 0:
                rel = ttbs.get(uid, med) / med
                f /= 1.0 + max(0.0, rel - 1.0) * SLOW_BIND_SHADE
            if len(counts) > 1 and mean > 0:
                imb = counts[node_domain.get(node, node)] / mean
                f /= 1.0 + max(0.0, imb - 1.0) * CROWDING_SHADE
        out.append(f)
    return out


def compute_regret(rows: Iterable[dict], evicted: Optional[set] = None,
                   node_domain: Optional[dict] = None) -> list[dict]:
    """Per-placement regret records over flattened placement rows
    (replay.iter_placement_rows shape). Only bound placements that
    carry at least one alternative OTHER than the chosen node
    participate — a row without a counterfactual has nothing to regret
    against. When the chosen node's own entry rides the alt list (the
    export keeps it wherever top_k surfaced it), that entry is the
    chosen value's basis — on the auction path the alt scores are
    end-state attributed while the row's "score" is the decision-round
    win, and regret must compare both sides on ONE basis. Each record:
    {"uid", "node", "t", "score", "best_alt", "outcome", "regret"}."""
    rows = list(rows)
    factors = outcome_factors(rows, evicted, node_domain)
    out = []
    for r, f in zip(rows, factors):
        node = r.get("node")
        alts = r.get("alt") or []
        others = [float(s) for n, s in alts if n != node]
        if node is None or not others:
            continue
        best_alt = max(others)
        chosen_basis = next((float(s) for n, s in alts if n == node),
                            float(r.get("score", 0.0)))
        chosen = chosen_basis * f
        out.append({"uid": r.get("uid", ""), "node": node,
                    "t": float(r.get("t", 0.0)),
                    "score": chosen_basis, "best_alt": best_alt,
                    "outcome": round(f, 6),
                    "regret": max(0.0, best_alt - chosen)})
    return out


def summarize_regret(records: list[dict]) -> dict:
    """{count, regret_mean, regret_p50, regret_p99,
    regret_positive_frac} over compute_regret records — the shape the
    bench artifact rows, the loop metrics, and checkpoint meta embed."""
    if not records:
        return {"count": 0, "regret_mean": 0.0, "regret_p50": 0.0,
                "regret_p99": 0.0, "regret_positive_frac": 0.0}
    reg = np.asarray([r["regret"] for r in records], np.float64)
    return {
        "count": int(reg.size),
        "regret_mean": round(float(reg.mean()), 4),
        "regret_p50": round(float(np.percentile(reg, 50)), 4),
        "regret_p99": round(float(np.percentile(reg, 99)), 4),
        "regret_positive_frac": round(float((reg > 0).mean()), 4),
    }


def harvest_hub_outcomes(hub) -> tuple[set, dict]:
    """(evicted_uids, node -> topology domain) from a LIVE in-process
    hub — the perf harness's analog of replay.wal_outcomes: bound-pod
    DELETE events in the journal are the eviction signal, node labels
    map to zone (hostname fallback) domains. A compacted journal
    (too_old) yields partial eviction data; domains stay complete."""
    evicted: set = set()
    node_domain: dict = {}
    try:
        for n in hub.list_nodes():
            labels = n.metadata.labels or {}
            node_domain[n.metadata.name] = labels.get(
                ZONE_LABEL, labels.get(HOSTNAME_LABEL, n.metadata.name))
    except Exception:  # noqa: BLE001 — hub variant without list_nodes
        pass
    try:
        ans = hub.list_changes(0, kinds=("pods",))
        if not ans.get("too_old"):
            for ch in ans.get("changes", []):
                if ch.get("type") != "delete":
                    continue
                obj = ch.get("obj")
                if obj is not None and getattr(obj.spec, "node_name", ""):
                    evicted.add(obj.metadata.uid)
    except Exception:  # noqa: BLE001 — hub variant without a journal
        pass
    return evicted, node_domain


# ------------------------------------------------ gate replay scoring


def replay_quality(params, rows: list[dict],
                   evicted: Optional[set] = None,
                   node_domain: Optional[dict] = None,
                   latency_repeats: int = 3) -> dict:
    """Score one policy's quality on held-out placement rows (see
    module docstring): preference-mass demerits per quality axis, lower
    is better, plus the batch-eval latency probe. Scored rows must
    carry feature vectors (the gate's holdout is feature-exported);
    failed-attempt anchor rows (node None) should ride along — they
    establish first_seen for the time-to-bind axis."""
    evicted = evicted or set()
    node_domain = node_domain or {}
    rows = list(rows)
    placed = [r for r in rows
              if r.get("node") is not None and r.get("feat")
              and len(r["feat"]) == NUM_FEATURES]
    if not placed:
        raise ValueError("no held-out placement rows with feature "
                         "vectors to replay-score against")
    x = np.asarray([r["feat"] for r in placed], np.float32)
    lat = float("inf")
    for _ in range(max(1, latency_repeats)):
        t0 = time.perf_counter()
        s = np_mlp(params, x)
        lat = min(lat, time.perf_counter() - t0)
    s = np.clip(s, 0.0, MAX_SCORE)
    # preference mass: a policy "prefers" the placements it scores
    # high; the +eps floor keeps an all-zero scorer uniform instead of
    # degenerate
    w = s.astype(np.float64) + 1e-3
    w_sum = float(w.sum())
    ev = np.asarray([1.0 if r.get("uid", "") in evicted else 0.0
                     for r in placed])
    counts, mean = _domain_counts(placed, node_domain)
    crowd = np.asarray([
        max(0.0, counts[node_domain.get(r["node"], r["node"])] / mean
            - 1.0) if mean > 0 else 0.0
        for r in placed])
    # anchored on ALL rows (incl. node=None failed attempts), not just
    # the scored placements — a placement row alone makes every
    # time-to-bind collapse to 0 and the axis permanently tie
    ttbs = _ttb_map(rows)
    ttb = np.asarray([ttbs.get(r.get("uid", ""), 0.0) for r in placed])
    # preference-weighted p99 of time-to-bind: sort by ttb, walk the
    # preference mass to the 99th percentile
    order = np.argsort(ttb)
    cum = np.cumsum(w[order])
    idx = int(np.searchsorted(cum, 0.99 * w_sum))
    ttb_p99 = float(ttb[order][min(idx, len(placed) - 1)])
    return {
        "preemptions": round(float((w * ev).sum() / w_sum), 6),
        "spread": round(float((w * crowd).sum() / w_sum), 6),
        "time_to_bind_p99_s": round(ttb_p99, 6),
        "latency_s": lat,
        "rows": len(placed),
    }


def gate_candidate(cand_params, live_params, rows: list[dict],
                   evicted: Optional[set] = None,
                   node_domain: Optional[dict] = None,
                   quality_eps: float = 0.01,
                   latency_budget: float = 0.5,
                   latency_floor_s: float = 1e-4) -> dict:
    """The promotion verdict: replay-score candidate vs live on the
    held-out rows. Promote when the candidate wins ≥2 of the 3 quality
    metrics — or strictly improves ≥1 with zero regressions, the
    clean-traffic escape hatch where a metric axis is degenerate (no
    evictions at all ties preemptions forever) — at latency parity
    (candidate batch-eval ≤ live × (1 + budget), with an absolute
    floor so microsecond jitter on tiny stacks can't fail parity).
    ``live_params is None`` is the bootstrap: nothing is serving, the
    first trained candidate promotes unconditionally."""
    if live_params is None:
        return {"promote": True, "bootstrap": True, "wins": [],
                "losses": [], "latency_ok": True}
    qc = replay_quality(cand_params, rows, evicted, node_domain)
    ql = replay_quality(live_params, rows, evicted, node_domain)
    wins, losses = [], []
    for k in QUALITY_METRICS:
        margin = quality_eps * max(abs(ql[k]), abs(qc[k]), 1e-6)
        if qc[k] < ql[k] - margin:
            wins.append(k)
        elif qc[k] > ql[k] + margin:
            losses.append(k)
    latency_ok = (qc["latency_s"]
                  <= ql["latency_s"] * (1.0 + latency_budget)
                  + latency_floor_s)
    promote = latency_ok and (len(wins) >= 2
                              or (len(wins) >= 1 and not losses))
    return {"promote": promote, "bootstrap": False,
            "wins": wins, "losses": losses, "latency_ok": latency_ok,
            "candidate": qc, "live": ql}
