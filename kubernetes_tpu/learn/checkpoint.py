"""Versioned learned-scorer checkpoints + the hot-reload watcher.

Format (JSON, one document): layer weights as nested lists so the file
is inspectable and diff-able; small by construction (the default scorer
is a few hundred floats).

    {
      "format_version": 1,
      "feature_version": 1,          # ops.learned.FEATURE_VERSION
      "num_features": 7,
      "layers": [{"w": [[...]], "b": [...]}, ...],
      "meta": {"seed": 0, "hidden": [8], "examples": 1234,
               "version": 3, "created": 1700000000.0, ...}
    }

Validation on load covers structure (format/feature version, shape
chain F -> h1 -> ... -> 1, parseable floats) AND finiteness: a NaN/Inf
weight anywhere rejects the file with CheckpointError, so a diverged
training run can never become the watcher's "last good" params — the
params are a few hundred floats, the isfinite scan is free. The device
guard reduction remains the runtime net for params that go bad past
the loader (in-memory corruption, future loader gaps): a poisoned
launch degrades that batch down the fallback ladder, proven by test.

Saves are atomic (tmp file + os.replace) so the scheduler's mtime-based
hot reload can never observe a torn write.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

import numpy as np

from kubernetes_tpu.ops.learned import FEATURE_VERSION, NUM_FEATURES

CHECKPOINT_VERSION = 1


class CheckpointError(ValueError):
    """The checkpoint file is unreadable, malformed, or trained against
    an incompatible feature layout."""


def _fingerprint(doc: dict) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


def save_checkpoint(path: str, params, meta: Optional[dict] = None) -> dict:
    """Write ``params`` (a ((W, b), ...) layer stack of array-likes) to
    ``path`` atomically; returns the document written (fingerprint
    included in meta)."""
    layers = []
    for w, b in params:
        w = np.asarray(w, np.float32)
        b = np.asarray(b, np.float32)
        layers.append({"w": w.tolist(), "b": b.tolist()})
    doc = {
        "format_version": CHECKPOINT_VERSION,
        "feature_version": FEATURE_VERSION,
        "num_features": int(np.asarray(params[0][0]).shape[0]),
        "layers": layers,
        "meta": dict(meta or {}),
    }
    doc["meta"].setdefault("created", time.time())
    doc["meta"]["fingerprint"] = _fingerprint(
        {"layers": layers, "feature_version": FEATURE_VERSION})
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc


def load_checkpoint(path: str):
    """Returns (params, meta): params a ((W, b), ...) tuple of float32
    numpy arrays, meta the document's meta dict plus format fields.
    Raises CheckpointError on any structural problem."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable checkpoint {path}: {e}") from e
    if not isinstance(doc, dict):
        raise CheckpointError(f"{path}: not a checkpoint document")
    fv = doc.get("format_version")
    if fv != CHECKPOINT_VERSION:
        raise CheckpointError(
            f"{path}: format_version {fv!r} != {CHECKPOINT_VERSION}")
    featv = doc.get("feature_version")
    if featv != FEATURE_VERSION:
        raise CheckpointError(
            f"{path}: feature_version {featv!r} != {FEATURE_VERSION} "
            "(retrain against the current feature layout)")
    layers = doc.get("layers")
    if not isinstance(layers, list) or not layers:
        raise CheckpointError(f"{path}: empty/missing layers")
    params = []
    prev = NUM_FEATURES
    for i, layer in enumerate(layers):
        try:
            w = np.asarray(layer["w"], np.float32)
            b = np.asarray(layer["b"], np.float32)
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointError(f"{path}: layer {i} malformed: {e}") \
                from e
        if w.ndim != 2 or b.ndim != 1 or w.shape[1] != b.shape[0]:
            raise CheckpointError(
                f"{path}: layer {i} shape mismatch {w.shape}/{b.shape}")
        if w.shape[0] != prev:
            raise CheckpointError(
                f"{path}: layer {i} expects {w.shape[0]} inputs, "
                f"got {prev}")
        if not (np.isfinite(w).all() and np.isfinite(b).all()):
            raise CheckpointError(
                f"{path}: layer {i} carries non-finite weights "
                "(diverged training run?)")
        prev = w.shape[1]
        params.append((w, b))
    if prev != 1:
        raise CheckpointError(f"{path}: head must be scalar, got {prev}")
    meta = dict(doc.get("meta") or {})
    meta["format_version"] = fv
    meta["feature_version"] = featv
    return tuple(params), meta


def next_version(path: str) -> int:
    """One past the version of the checkpoint currently at ``path``
    (1 when absent/unreadable) — the auto-bump behind ``learn train``
    and the loop daemon, so a forgotten ``--version`` flag can never
    republish version 1 over a live v7 and walk the
    scheduler_learned_checkpoint_version gauge backwards."""
    try:
        _, meta = load_checkpoint(path)
        return int(meta.get("version", 0)) + 1
    except (CheckpointError, TypeError, ValueError):
        return 1


class CheckpointWatcher:
    """mtime-polled checkpoint loader: ``poll()`` is a stat + compare
    (the scheduler calls it once per launch at snapshot-sync time); only
    an mtime/size change pays a load. A failed load KEEPS the previous
    params — a corrupt overwrite degrades to the last good scorer, and
    the error is counted for /debug/scorer and the metrics surface."""

    def __init__(self, path: str):
        self.path = path
        self.params = None          # last good ((W, b), ...) numpy stack
        self.meta: dict = {}
        self.loads = 0              # successful loads (first one included)
        self.load_errors = 0
        self.last_error: Optional[str] = None
        self._stamp = None          # (mtime_ns, size) last attempted

    def poll(self) -> bool:
        """Returns True when params changed (fresh load succeeded)."""
        try:
            st = os.stat(self.path)
        except OSError as e:
            # a missing checkpoint is NOT a load error: the normal
            # deployment order starts the scheduler before the offline
            # trainer publishes its first file ("waiting"); only a
            # previously-loaded checkpoint VANISHING is worth noting
            # (last good params keep serving either way)
            if self._stamp != () and self.params is not None:
                self.last_error = f"stat: {e}"
            self._stamp = ()
            return False
        stamp = (st.st_mtime_ns, st.st_size)
        if stamp == self._stamp:
            return False
        self._stamp = stamp
        try:
            self.params, self.meta = load_checkpoint(self.path)
        except CheckpointError as e:
            self.load_errors += 1
            self.last_error = str(e)
            if isinstance(e.__cause__, OSError):
                # transient READ failure (NFS blip, momentary
                # permissions): forget the stamp so the next poll
                # retries this version instead of skipping it until the
                # trainer happens to publish again. Parse/shape errors
                # keep the stamp — re-parsing a genuinely corrupt file
                # every cycle buys nothing.
                self._stamp = None
            return False
        self.loads += 1
        self.last_error = None
        return True
