"""Learned scoring subsystem: replay-trained MLP scorer for the device
pipeline, plus the CLOSED learning loop around it.

Five parts (ROADMAP items 5 and 4):

- ``learn.replay``: reconstruct training examples from flight-recorder
  trace exports (per-pod chosen-node feature rows + hand-tuned
  aggregate scores, export format v2) and outcome labels harvested from
  the hub's journal/WAL (evictions, topology-spread imbalance,
  time-to-bind).
- ``learn.train``: a small pure-JAX MLP trainer — behavior-cloning warm
  start on the hand-tuned aggregate, then reward-weighted fine-tune on
  the outcome labels; deterministic given a seed.
- ``learn.checkpoint``: the versioned on-disk checkpoint format plus the
  mtime-watching hot-reload helper the scheduler polls at
  snapshot-sync time.
- ``learn.regret``: per-placement regret (chosen outcome vs the best
  exported counterfactual alternative) and the promotion gate's
  replay scorer.
- ``learn.loop``: the retrain daemon — tail the rotating trace
  exports, retrain on a cadence (BC warm start + regret-weighted
  contextual-bandit fine-tune), gate candidates against the live
  checkpoint on held-out rows, promote winners, roll back on
  post-promotion regret regression.

The serving side lives in ``plugins/learned.py`` (the profile-gated
LearnedScore manager) and ``ops/learned.py`` (the fused device kernel).
CLI: ``python -m kubernetes_tpu.learn --help``.
"""

from kubernetes_tpu.learn.checkpoint import (  # noqa: F401
    CHECKPOINT_VERSION,
    CheckpointError,
    CheckpointWatcher,
    load_checkpoint,
    next_version,
    save_checkpoint,
)
from kubernetes_tpu.learn.replay import (  # noqa: F401
    ReplayDataset,
    build_dataset,
    build_dataset_rows,
    iter_placement_rows,
    synthetic_dataset,
    wal_outcomes,
)
from kubernetes_tpu.learn.train import TrainConfig, train  # noqa: F401
