"""Lease-based leader election.

The reference's only multi-process story (cmd/kube-scheduler/app/
server.go:284-317 + k8s.io/client-go/tools/leaderelection): candidate
schedulers race to acquire a coordination Lease; the holder renews it
every renew_interval and everyone else watches for expiry. The hub is the
lease store (a real deployment would point this at the apiserver).

Defaults mirror the reference's component config: 15s lease duration,
10s renew deadline, 2s retry period.

The store may be REMOTE (RemoteHub.leases over HTTP): every store call
can raise a transport error. A failed or unreachable renew is treated as
"not leading" — never as a crash of the maintenance loop — and a holder
that cannot renew within ``renew_deadline`` steps down voluntarily
(leaderelection.go's RenewDeadline contract) so a healthy peer takes
over within the lease duration instead of waiting out a zombie.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.leaderelection")

RING_SLOTS = 64              # virtual slots on the namespace crc32 ring
SCHEDULER_TTL_S = 10.0       # a scheduler replica missing heartbeats
#                              this long loses its slices to the others
SCHED_SLICE_LEASE = "kube-scheduler-slices"   # the slice-map fence lease


def ring_slot(namespace: str, ring_size: int = RING_SLOTS) -> int:
    """Deterministic namespace → ring slot (crc32, NOT Python's
    randomized hash: the mapping must survive restarts and agree
    between every router, shard, and scheduler replica). Shared by the
    pod-shard ring (fabric.cluster) and the scheduler slice ring — the
    two consumers partition on the same hash so operators reason about
    one placement function."""
    return zlib.crc32(namespace.encode("utf-8")) % ring_size


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, the slice leader election uses.

    ``epoch`` is the fencing token (the etcd/Chubby sequencer): the store
    stamps a fresh, monotonically increasing value on every ACQUISITION
    (holder change), never on renewals. Writers attach their epoch to
    fenced hub writes (``Hub.bind``/``patch_pod_condition``); the hub
    rejects any epoch older than the newest issued, so a deposed
    leader's in-flight async binds can never land after failover."""

    name: str = ""
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0
    epoch: int = 0


class LeaseStore:
    """The hub-side lease registry (get-or-create + compare-and-swap by
    holder, which is all leaderelection needs). Issues fencing epochs:
    one monotonic counter per lease name, bumped on holder change."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._leases: dict[str, Lease] = {}
        # newest epoch ever ISSUED per lease name — survives a released
        # (vacated) lease, so re-acquisition always moves forward
        self._epochs: dict[str, int] = {}

    def get(self, name: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(name)
            return None if lease is None else Lease(**vars(lease))

    def epoch_of(self, name: str) -> int:
        """Newest fencing epoch issued for ``name`` (0 = never held)."""
        with self._lock:
            return self._epochs.get(name, 0)

    def dump(self) -> dict:
        """Snapshot the store (the replicated state core's log
        compaction persists this alongside the rv counter and ring)."""
        with self._lock:
            return {"leases": {n: Lease(**vars(lease))
                               for n, lease in self._leases.items()},
                    "epochs": dict(self._epochs)}

    def restore(self, snap: dict) -> None:
        """Replace the store's contents from a ``dump()`` snapshot."""
        with self._lock:
            self._leases = {n: Lease(**vars(lease))
                            for n, lease in snap.get("leases",
                                                     {}).items()}
            self._epochs = {n: int(e)
                            for n, e in snap.get("epochs", {}).items()}

    def update(self, lease: Lease, expect_holder: Optional[str]) -> bool:
        """CAS: apply iff the stored holder matches ``expect_holder``
        (None = lease must not exist yet or be the same holder). The
        STORE owns the epoch: a holder change stamps the next fencing
        token; a renewal (same holder) carries the current one forward
        regardless of what the caller passed."""
        with self._lock:
            cur = self._leases.get(lease.name)
            if cur is not None and expect_holder is not None \
                    and cur.holder_identity != expect_holder:
                return False
            if cur is not None and expect_holder is None \
                    and cur.holder_identity not in ("",
                                                    lease.holder_identity):
                return False
            stored = Lease(**vars(lease))
            prev_holder = cur.holder_identity if cur is not None else ""
            if stored.holder_identity and \
                    stored.holder_identity != prev_holder:
                # acquisition (vacant -> holder or steal): new epoch
                nxt = self._epochs.get(lease.name, 0) + 1
                self._epochs[lease.name] = nxt
                stored.epoch = nxt
            elif cur is not None:
                stored.epoch = cur.epoch
            self._leases[lease.name] = stored
            return True


class LeaderElector:
    """tools/leaderelection.LeaderElector reduced to the scheduler's use:
    tryAcquireOrRenew on a timer; is_leader() gates the scheduling loop."""

    def __init__(self, store: LeaseStore, identity: str,
                 lease_name: str = "kube-scheduler",
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 2.0,
                 now: Callable[[], float] = time.time,
                 on_started_leading: Optional[Callable] = None,
                 on_stopped_leading: Optional[Callable] = None):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        # client-go validates LeaseDuration > RenewDeadline; clamp to
        # the reference's 2/3 ratio so a short --lease-duration cannot
        # open a dual-leader window (peer steals at lease_duration while
        # we still think the renew deadline hasn't passed)
        self.renew_deadline = min(renew_deadline, lease_duration * 2 / 3)
        self.retry_period = retry_period
        self.now = now
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_try = 0.0
        self._last_renew = 0.0   # last SUCCESSFUL acquire/renew
        self.transport_errors = 0
        # fencing token of our newest acquisition. Deliberately NOT
        # cleared on step-down: in-flight writes must keep carrying the
        # epoch they were issued under so the hub can reject them after
        # a peer acquires a newer one.
        self.epoch = 0

    def is_leader(self) -> bool:
        return self._leading

    def _enforce_renew_deadline(self, now: float) -> None:
        """RenewDeadline exceeded: we may still hold the lease in the
        store, but we can no longer PROVE it — step down before a peer's
        clock says we expired (split-brain guard)."""
        if self._leading and now - self._last_renew > self.renew_deadline:
            logger.warning("leaderelection: renew deadline exceeded "
                           "(%.1fs), stepping down", self.renew_deadline)
            self._set_leading(False)

    def try_acquire_or_renew(self) -> bool:
        """leaderelection.go tryAcquireOrRenew: renew our own lease, or
        take an expired/vacant one. A store that cannot be reached is a
        failed renew (not leading), never an escaping exception."""
        now = self.now()
        self._enforce_renew_deadline(now)
        # the try wraps ONLY store I/O: a raising user callback in
        # _set_leading must surface as itself, not masquerade as a
        # transport failure (and flap leadership forever)
        try:
            acquired = False
            cur = self.store.get(self.lease_name)
            if cur is None or not cur.holder_identity:
                ok = self.store.update(Lease(
                    name=self.lease_name, holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now), expect_holder=None)
                acquired = ok
            elif cur.holder_identity == self.identity:
                cur.renew_time = now
                # a failed CAS means a peer stole the lease while we
                # stalled: step down immediately (split-brain guard)
                ok = self.store.update(cur, expect_holder=self.identity)
                if ok:
                    self.epoch = cur.epoch
            elif now - cur.renew_time > cur.lease_duration_seconds:
                # expired: steal it (lease_transitions counts takeovers)
                ok = self.store.update(Lease(
                    name=self.lease_name, holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now,
                    lease_transitions=cur.lease_transitions + 1),
                    expect_holder=cur.holder_identity)
                acquired = ok
            else:
                ok = False
            if acquired:
                # the store stamped our fencing epoch during the CAS;
                # read it back (a racing steal leaves a stale epoch here,
                # which is exactly what fencing then rejects). The
                # read-back gets its own guard: the CAS already
                # succeeded, so a transport blip HERE must not demote a
                # holder — it just leaves the (older, safely fenced)
                # epoch until the next renew's read.
                try:
                    got = self.store.get(self.lease_name)
                    if got is not None \
                            and got.holder_identity == self.identity:
                        self.epoch = got.epoch
                except Exception as e:  # noqa: BLE001 — transport only
                    self.transport_errors += 1
                    logger.warning("leaderelection: epoch read-back "
                                   "failed (%r); keeping prior epoch", e)
        except Exception as e:  # noqa: BLE001 — remote store transport
            # failure: an unreachable store means we cannot renew; we are
            # not leading until it answers again
            self.transport_errors += 1
            logger.warning("leaderelection: lease store unreachable "
                           "(%r); treating as not leading", e)
            ok = False
        if ok:
            self._last_renew = now
        self._set_leading(ok)
        return self._leading

    def tick(self) -> bool:
        """Rate-limited try_acquire_or_renew for the maintenance loop.
        Exception-safe: transport errors demote, they never escape."""
        now = self.now()
        if now - self._last_try < self.retry_period:
            # don't coast on a stale lease between retries
            self._enforce_renew_deadline(now)
            return self._leading
        self._last_try = now
        return self.try_acquire_or_renew()

    def release(self) -> None:
        """Step down voluntarily (leaderelection.go release): zero out the
        holder so a peer acquires without waiting for expiry. Best-effort
        over an unreachable store — local demotion always happens."""
        if not self._leading:
            return
        try:
            self.store.update(Lease(
                name=self.lease_name, holder_identity="",
                lease_duration_seconds=self.lease_duration,
                acquire_time=0.0, renew_time=0.0),
                expect_holder=self.identity)
        except Exception as e:  # noqa: BLE001 — the lease then simply
            # expires on its own; peers take over within lease_duration
            self.transport_errors += 1
            logger.warning("leaderelection: release failed (%r); lease "
                           "will expire naturally", e)
        self._set_leading(False)

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()


# --------------------------------------------------------------------------
# horizontal scale-out: the slice board + slice-lease manager
# --------------------------------------------------------------------------


class SliceBoard:
    """The scheduler-replica registry + pending-pod slice ring — the
    state core's crc32 ring machinery generalized to its second
    consumer. Replicas heartbeat into the registry (soft state, TTL'd
    like relays); the ring maps each of the ``RING_SLOTS`` namespace
    slots to the replica that drains it, CAS'd by epoch so two
    replicas racing a rebalance cannot both win.

    Lives on the in-process ``Hub`` and the fabric's ``StateCore``.
    The replicated ``StateReplica`` keeps the RING in its log-applied
    state machine instead (the ``sched_ring.set`` op — a slice map
    must survive leader failover) and gossips only the registry."""

    def __init__(self, ring_slots: int = RING_SLOTS) -> None:
        self._lock = threading.Lock()
        self.ring_slots = ring_slots
        self._ring: dict = {"epoch": 0, "slots": []}
        self._schedulers: dict[str, dict] = {}

    def register(self, name: str, url: str = "",
                 pid: int | None = None) -> dict:
        """Heartbeat-register a scheduler replica; returns the current
        slice ring so one round-trip both announces and refreshes."""
        with self._lock:
            self._schedulers[name] = {"name": name, "url": url,
                                      "pid": pid, "ts": time.time()}
            return {"ring": {"epoch": self._ring["epoch"],
                             "slots": list(self._ring["slots"])}}

    def unregister(self, name: str) -> dict:
        """Graceful departure: drop the registration so peers re-home
        the replica's slices now instead of waiting out the TTL."""
        with self._lock:
            self._schedulers.pop(name, None)
            return {"ok": True}

    def schedulers(self) -> dict:
        with self._lock:
            return {n: dict(s) for n, s in self._schedulers.items()}

    def live(self, ttl_s: float = SCHEDULER_TTL_S) -> dict:
        """Registrations with a heartbeat inside ``ttl_s`` (the served
        topology row set)."""
        now = time.time()
        with self._lock:
            return {n: dict(s) for n, s in self._schedulers.items()
                    if now - s["ts"] <= ttl_s}

    def ring(self) -> dict:
        with self._lock:
            return {"epoch": self._ring["epoch"],
                    "slots": list(self._ring["slots"])}

    def set_ring(self, ring: dict, expect_epoch: int) -> bool:
        """CAS by epoch — identical discipline to the pod-shard ring."""
        with self._lock:
            if self._ring["epoch"] != int(expect_epoch):
                return False
            self._ring = {"epoch": int(ring["epoch"]),
                          "slots": list(ring["slots"])}
            return True


def rebalance_slots(slots: list, live: list[str],
                    ring_slots: int = RING_SLOTS) -> list:
    """Minimal-churn slice assignment: every slot owned by a live
    replica stays put (up to an even ceiling), orphaned and overflow
    slots go to the least-loaded live replica. Deterministic, so every
    replica computing the next map from the same inputs proposes the
    same CAS — racers collide on the epoch, not on divergent maps."""
    live_sorted = sorted(set(live))
    if not live_sorted:
        return list(slots)
    size = len(slots) or ring_slots
    target = -(-size // len(live_sorted))      # ceil
    counts = {r: 0 for r in live_sorted}
    out = list(slots) + [None] * (size - len(slots))
    for i, owner in enumerate(out):
        if owner in counts and counts[owner] < target:
            counts[owner] += 1
        else:
            out[i] = None
    for i, owner in enumerate(out):
        if owner is None:
            r = min(live_sorted, key=lambda x: (counts[x], x))
            out[i] = r
            counts[r] += 1
    return out


class SliceManager:
    """The elector generalized to N concurrent scheduler replicas: each
    replica heartbeats into the slice board, rebalances the slice ring
    when the live set changes (join/death — exactly the pod-shard
    rebalance discipline), and drains only pods whose namespace hashes
    into its owned slots.

    Presents the ``LeaderElector`` surface (``tick``/``is_leader``/
    ``release``/``epoch``/``lease_name``/``retry_period``) so
    ``Scheduler.run`` gates on it unchanged; ``epoch`` is the fencing
    token of the SLICE lease, whose holder identity encodes the ring
    epoch — every committed rebalance is a holder change, so the lease
    store stamps a fresh fencing epoch and every bind submitted under
    the OLD map loses the fence and requeues (``hub.bind``'s
    deposed-leader path). Fencing here is the belt; the hub's bind-once
    ``Conflict`` is the suspenders — correctness never depends on
    replicas coordinating in-band, so a stale map only costs a requeue.

    Single-replica deployments keep using ``LeaderElector`` (or no
    elector at all): this class is the scale-out rung, not a
    replacement for the fallback."""

    is_slice_manager = True

    def __init__(self, hub, identity: str, url: str = "",
                 lease_name: str = SCHED_SLICE_LEASE,
                 heartbeat_s: float = 2.0,
                 ttl_s: float = SCHEDULER_TTL_S,
                 ring_slots: int = RING_SLOTS,
                 now: Callable[[], float] = time.time):
        self.hub = hub
        self.identity = identity
        self.url = url
        self.lease_name = lease_name
        self.heartbeat_s = heartbeat_s
        self.retry_period = heartbeat_s   # Scheduler.run's idle wait
        self.ttl_s = ttl_s
        self.ring_slots = ring_slots
        self.now = now
        # fencing token captured WITH the slice map observation (binds
        # carry it; a later rebalance bumps the lease past it)
        self.epoch = 0
        self.ring_epoch = 0
        self.owned: frozenset = frozenset()
        self.generation = 0        # bumps whenever `owned` changes
        self.rebalances = 0        # maps THIS replica CAS'd in
        self.transport_errors = 0
        self._slots: list = []
        self._leading = False
        self._last_try = 0.0
        self._last_ok = 0.0

    # ------------- elector surface -------------

    def is_leader(self) -> bool:
        return self._leading

    def tick(self) -> bool:
        """Rate-limited heartbeat + rebalance check. Exception-safe:
        transport errors keep the CURRENT slices until the TTL runs out
        (the registry's own expiry clock — a blip must not stall the
        drain; past the TTL peers have re-homed our slices, so
        continuing to schedule them would only burn fenced binds)."""
        now = self.now()
        if now - self._last_try < self.heartbeat_s:
            if self._leading and now - self._last_ok > self.ttl_s:
                self._leading = False
            return self._leading
        self._last_try = now
        try:
            self._heartbeat(now)
            self._last_ok = now
            self._leading = bool(self.owned)
        except Exception as e:  # noqa: BLE001 — remote board transport
            self.transport_errors += 1
            logger.warning("slices: board unreachable (%r)", e)
            if now - self._last_ok > self.ttl_s:
                self._leading = False
        return self._leading

    def release(self) -> None:
        """Graceful departure: deregister and re-home our slices NOW so
        peers pick up the pending backlog without waiting out the TTL.
        Best-effort over an unreachable board — the registration then
        simply expires and peers rebalance on their own clock."""
        self._leading = False
        if self.owned:
            self.owned = frozenset()
            self.generation += 1
        try:
            hub = self.hub
            hub.fabric_unregister_scheduler(self.identity)
            live = [n for n in self._live_replicas(self.now())
                    if n != self.identity]
            if live:
                self._maybe_rebalance(hub.fabric_sched_ring(), live)
        except Exception as e:  # noqa: BLE001 — TTL expiry heals it
            self.transport_errors += 1
            logger.warning("slices: release failed (%r); slices "
                           "re-home at the registry TTL", e)

    # ------------- partition surface (the scheduler's filter) -------------

    def owns_namespace(self, namespace: str) -> bool:
        slots = self._slots
        if not slots:
            return False
        return slots[ring_slot(namespace, len(slots))] == self.identity

    def owned_slots(self) -> frozenset:
        return self.owned

    # ------------- internals -------------

    def _live_replicas(self, now: float) -> list:
        regs = self.hub.fabric_schedulers()
        live = [n for n, r in regs.items()
                if now - float(r.get("ts", 0.0)) <= self.ttl_s]
        if self.identity not in live:
            live.append(self.identity)
        return live

    def _heartbeat(self, now: float) -> None:
        reg = self.hub.fabric_register_scheduler(
            self.identity, self.url, os.getpid())
        ring = reg.get("ring") or {"epoch": 0, "slots": []}
        ring = self._maybe_rebalance(ring, self._live_replicas(now))
        # the fence must track the map: a committed rebalance whose
        # lease bump was lost to a transport blip would leave deposed
        # owners unfenced (bind-once still protects; this restores the
        # belt), so the sync re-runs until holder matches ring epoch
        self._sync_fence(int(ring.get("epoch", 0)), now)
        self.epoch = int(self.hub.leases.epoch_of(self.lease_name))
        self.ring_epoch = int(ring.get("epoch", 0))
        self._slots = list(ring.get("slots") or [])
        owned = frozenset(i for i, o in enumerate(self._slots)
                          if o == self.identity)
        if owned != self.owned:
            self.owned = owned
            self.generation += 1

    def _maybe_rebalance(self, ring: dict, live: list) -> dict:
        slots = list(ring.get("slots") or [])
        epoch = int(ring.get("epoch", 0))
        if not live:
            return ring
        want = rebalance_slots(slots, live, self.ring_slots)
        if want == slots:
            return ring
        new_ring = {"epoch": epoch + 1, "slots": want}
        if bool(self.hub.fabric_set_sched_ring(new_ring, epoch)):
            self.rebalances += 1
            return new_ring
        # lost the CAS: a peer rebalanced first — adopt the winner's map
        return self.hub.fabric_sched_ring()

    def _sync_fence(self, ring_epoch: int, now: float) -> None:
        """Mirror the slice-map epoch into the slice lease: the lease
        store stamps fencing epochs on HOLDER change, so the holder
        identity encodes the ring epoch — each rebalance is exactly one
        holder change, and a re-applied sync is none."""
        holder = f"slices@{ring_epoch}"
        cur = self.hub.leases.get(self.lease_name)
        cur_holder = cur.holder_identity if cur is not None else None
        if cur_holder == holder:
            return
        self.hub.leases.update(Lease(
            name=self.lease_name, holder_identity=holder,
            lease_duration_seconds=self.ttl_s,
            acquire_time=now, renew_time=now), cur_holder)
