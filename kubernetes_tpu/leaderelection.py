"""Lease-based leader election.

The reference's only multi-process story (cmd/kube-scheduler/app/
server.go:284-317 + k8s.io/client-go/tools/leaderelection): candidate
schedulers race to acquire a coordination Lease; the holder renews it
every renew_interval and everyone else watches for expiry. The hub is the
lease store (a real deployment would point this at the apiserver).

Defaults mirror the reference's component config: 15s lease duration,
10s renew deadline, 2s retry period.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, the slice leader election uses."""

    name: str = ""
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


class LeaseStore:
    """The hub-side lease registry (get-or-create + compare-and-swap by
    holder, which is all leaderelection needs)."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._leases: dict[str, Lease] = {}

    def get(self, name: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(name)
            return None if lease is None else Lease(**vars(lease))

    def update(self, lease: Lease, expect_holder: Optional[str]) -> bool:
        """CAS: apply iff the stored holder matches ``expect_holder``
        (None = lease must not exist yet or be the same holder)."""
        with self._lock:
            cur = self._leases.get(lease.name)
            if cur is not None and expect_holder is not None \
                    and cur.holder_identity != expect_holder:
                return False
            if cur is not None and expect_holder is None \
                    and cur.holder_identity not in ("",
                                                    lease.holder_identity):
                return False
            self._leases[lease.name] = Lease(**vars(lease))
            return True


class LeaderElector:
    """tools/leaderelection.LeaderElector reduced to the scheduler's use:
    tryAcquireOrRenew on a timer; is_leader() gates the scheduling loop."""

    def __init__(self, store: LeaseStore, identity: str,
                 lease_name: str = "kube-scheduler",
                 lease_duration: float = 15.0,
                 retry_period: float = 2.0,
                 now: Callable[[], float] = time.time,
                 on_started_leading: Optional[Callable] = None,
                 on_stopped_leading: Optional[Callable] = None):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.now = now
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_try = 0.0

    def is_leader(self) -> bool:
        return self._leading

    def try_acquire_or_renew(self) -> bool:
        """leaderelection.go tryAcquireOrRenew: renew our own lease, or
        take an expired/vacant one."""
        now = self.now()
        cur = self.store.get(self.lease_name)
        if cur is None or not cur.holder_identity:
            ok = self.store.update(Lease(
                name=self.lease_name, holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now), expect_holder=None)
            self._set_leading(ok)
            return self._leading
        if cur.holder_identity == self.identity:
            cur.renew_time = now
            ok = self.store.update(cur, expect_holder=self.identity)
            # a failed CAS means a peer stole the lease while we stalled:
            # step down immediately (split-brain guard)
            self._set_leading(ok)
            return ok
        if now - cur.renew_time > cur.lease_duration_seconds:
            # expired: steal it (lease_transitions counts takeovers)
            ok = self.store.update(Lease(
                name=self.lease_name, holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now,
                lease_transitions=cur.lease_transitions + 1),
                expect_holder=cur.holder_identity)
            self._set_leading(ok)
            return self._leading
        self._set_leading(False)
        return False

    def tick(self) -> bool:
        """Rate-limited try_acquire_or_renew for the maintenance loop."""
        now = self.now()
        if now - self._last_try < self.retry_period:
            return self._leading
        self._last_try = now
        return self.try_acquire_or_renew()

    def release(self) -> None:
        """Step down voluntarily (leaderelection.go release): zero out the
        holder so a peer acquires without waiting for expiry."""
        if not self._leading:
            return
        self.store.update(Lease(
            name=self.lease_name, holder_identity="",
            lease_duration_seconds=self.lease_duration,
            acquire_time=0.0, renew_time=0.0), expect_holder=self.identity)
        self._set_leading(False)

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
