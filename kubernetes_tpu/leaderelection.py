"""Lease-based leader election.

The reference's only multi-process story (cmd/kube-scheduler/app/
server.go:284-317 + k8s.io/client-go/tools/leaderelection): candidate
schedulers race to acquire a coordination Lease; the holder renews it
every renew_interval and everyone else watches for expiry. The hub is the
lease store (a real deployment would point this at the apiserver).

Defaults mirror the reference's component config: 15s lease duration,
10s renew deadline, 2s retry period.

The store may be REMOTE (RemoteHub.leases over HTTP): every store call
can raise a transport error. A failed or unreachable renew is treated as
"not leading" — never as a crash of the maintenance loop — and a holder
that cannot renew within ``renew_deadline`` steps down voluntarily
(leaderelection.go's RenewDeadline contract) so a healthy peer takes
over within the lease duration instead of waiting out a zombie.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.leaderelection")


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, the slice leader election uses."""

    name: str = ""
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


class LeaseStore:
    """The hub-side lease registry (get-or-create + compare-and-swap by
    holder, which is all leaderelection needs)."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._leases: dict[str, Lease] = {}

    def get(self, name: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(name)
            return None if lease is None else Lease(**vars(lease))

    def update(self, lease: Lease, expect_holder: Optional[str]) -> bool:
        """CAS: apply iff the stored holder matches ``expect_holder``
        (None = lease must not exist yet or be the same holder)."""
        with self._lock:
            cur = self._leases.get(lease.name)
            if cur is not None and expect_holder is not None \
                    and cur.holder_identity != expect_holder:
                return False
            if cur is not None and expect_holder is None \
                    and cur.holder_identity not in ("",
                                                    lease.holder_identity):
                return False
            self._leases[lease.name] = Lease(**vars(lease))
            return True


class LeaderElector:
    """tools/leaderelection.LeaderElector reduced to the scheduler's use:
    tryAcquireOrRenew on a timer; is_leader() gates the scheduling loop."""

    def __init__(self, store: LeaseStore, identity: str,
                 lease_name: str = "kube-scheduler",
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 2.0,
                 now: Callable[[], float] = time.time,
                 on_started_leading: Optional[Callable] = None,
                 on_stopped_leading: Optional[Callable] = None):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        # client-go validates LeaseDuration > RenewDeadline; clamp to
        # the reference's 2/3 ratio so a short --lease-duration cannot
        # open a dual-leader window (peer steals at lease_duration while
        # we still think the renew deadline hasn't passed)
        self.renew_deadline = min(renew_deadline, lease_duration * 2 / 3)
        self.retry_period = retry_period
        self.now = now
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_try = 0.0
        self._last_renew = 0.0   # last SUCCESSFUL acquire/renew
        self.transport_errors = 0

    def is_leader(self) -> bool:
        return self._leading

    def _enforce_renew_deadline(self, now: float) -> None:
        """RenewDeadline exceeded: we may still hold the lease in the
        store, but we can no longer PROVE it — step down before a peer's
        clock says we expired (split-brain guard)."""
        if self._leading and now - self._last_renew > self.renew_deadline:
            logger.warning("leaderelection: renew deadline exceeded "
                           "(%.1fs), stepping down", self.renew_deadline)
            self._set_leading(False)

    def try_acquire_or_renew(self) -> bool:
        """leaderelection.go tryAcquireOrRenew: renew our own lease, or
        take an expired/vacant one. A store that cannot be reached is a
        failed renew (not leading), never an escaping exception."""
        now = self.now()
        self._enforce_renew_deadline(now)
        # the try wraps ONLY store I/O: a raising user callback in
        # _set_leading must surface as itself, not masquerade as a
        # transport failure (and flap leadership forever)
        try:
            cur = self.store.get(self.lease_name)
            if cur is None or not cur.holder_identity:
                ok = self.store.update(Lease(
                    name=self.lease_name, holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now), expect_holder=None)
            elif cur.holder_identity == self.identity:
                cur.renew_time = now
                # a failed CAS means a peer stole the lease while we
                # stalled: step down immediately (split-brain guard)
                ok = self.store.update(cur, expect_holder=self.identity)
            elif now - cur.renew_time > cur.lease_duration_seconds:
                # expired: steal it (lease_transitions counts takeovers)
                ok = self.store.update(Lease(
                    name=self.lease_name, holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now,
                    lease_transitions=cur.lease_transitions + 1),
                    expect_holder=cur.holder_identity)
            else:
                ok = False
        except Exception as e:  # noqa: BLE001 — remote store transport
            # failure: an unreachable store means we cannot renew; we are
            # not leading until it answers again
            self.transport_errors += 1
            logger.warning("leaderelection: lease store unreachable "
                           "(%r); treating as not leading", e)
            ok = False
        if ok:
            self._last_renew = now
        self._set_leading(ok)
        return self._leading

    def tick(self) -> bool:
        """Rate-limited try_acquire_or_renew for the maintenance loop.
        Exception-safe: transport errors demote, they never escape."""
        now = self.now()
        if now - self._last_try < self.retry_period:
            # don't coast on a stale lease between retries
            self._enforce_renew_deadline(now)
            return self._leading
        self._last_try = now
        return self.try_acquire_or_renew()

    def release(self) -> None:
        """Step down voluntarily (leaderelection.go release): zero out the
        holder so a peer acquires without waiting for expiry. Best-effort
        over an unreachable store — local demotion always happens."""
        if not self._leading:
            return
        try:
            self.store.update(Lease(
                name=self.lease_name, holder_identity="",
                lease_duration_seconds=self.lease_duration,
                acquire_time=0.0, renew_time=0.0),
                expect_holder=self.identity)
        except Exception as e:  # noqa: BLE001 — the lease then simply
            # expires on its own; peers take over within lease_duration
            self.transport_errors += 1
            logger.warning("leaderelection: release failed (%r); lease "
                           "will expire naturally", e)
        self._set_leading(False)

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
