"""Lease-based leader election.

The reference's only multi-process story (cmd/kube-scheduler/app/
server.go:284-317 + k8s.io/client-go/tools/leaderelection): candidate
schedulers race to acquire a coordination Lease; the holder renews it
every renew_interval and everyone else watches for expiry. The hub is the
lease store (a real deployment would point this at the apiserver).

Defaults mirror the reference's component config: 15s lease duration,
10s renew deadline, 2s retry period.

The store may be REMOTE (RemoteHub.leases over HTTP): every store call
can raise a transport error. A failed or unreachable renew is treated as
"not leading" — never as a crash of the maintenance loop — and a holder
that cannot renew within ``renew_deadline`` steps down voluntarily
(leaderelection.go's RenewDeadline contract) so a healthy peer takes
over within the lease duration instead of waiting out a zombie.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.leaderelection")


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, the slice leader election uses.

    ``epoch`` is the fencing token (the etcd/Chubby sequencer): the store
    stamps a fresh, monotonically increasing value on every ACQUISITION
    (holder change), never on renewals. Writers attach their epoch to
    fenced hub writes (``Hub.bind``/``patch_pod_condition``); the hub
    rejects any epoch older than the newest issued, so a deposed
    leader's in-flight async binds can never land after failover."""

    name: str = ""
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0
    epoch: int = 0


class LeaseStore:
    """The hub-side lease registry (get-or-create + compare-and-swap by
    holder, which is all leaderelection needs). Issues fencing epochs:
    one monotonic counter per lease name, bumped on holder change."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._leases: dict[str, Lease] = {}
        # newest epoch ever ISSUED per lease name — survives a released
        # (vacated) lease, so re-acquisition always moves forward
        self._epochs: dict[str, int] = {}

    def get(self, name: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(name)
            return None if lease is None else Lease(**vars(lease))

    def epoch_of(self, name: str) -> int:
        """Newest fencing epoch issued for ``name`` (0 = never held)."""
        with self._lock:
            return self._epochs.get(name, 0)

    def dump(self) -> dict:
        """Snapshot the store (the replicated state core's log
        compaction persists this alongside the rv counter and ring)."""
        with self._lock:
            return {"leases": {n: Lease(**vars(lease))
                               for n, lease in self._leases.items()},
                    "epochs": dict(self._epochs)}

    def restore(self, snap: dict) -> None:
        """Replace the store's contents from a ``dump()`` snapshot."""
        with self._lock:
            self._leases = {n: Lease(**vars(lease))
                            for n, lease in snap.get("leases",
                                                     {}).items()}
            self._epochs = {n: int(e)
                            for n, e in snap.get("epochs", {}).items()}

    def update(self, lease: Lease, expect_holder: Optional[str]) -> bool:
        """CAS: apply iff the stored holder matches ``expect_holder``
        (None = lease must not exist yet or be the same holder). The
        STORE owns the epoch: a holder change stamps the next fencing
        token; a renewal (same holder) carries the current one forward
        regardless of what the caller passed."""
        with self._lock:
            cur = self._leases.get(lease.name)
            if cur is not None and expect_holder is not None \
                    and cur.holder_identity != expect_holder:
                return False
            if cur is not None and expect_holder is None \
                    and cur.holder_identity not in ("",
                                                    lease.holder_identity):
                return False
            stored = Lease(**vars(lease))
            prev_holder = cur.holder_identity if cur is not None else ""
            if stored.holder_identity and \
                    stored.holder_identity != prev_holder:
                # acquisition (vacant -> holder or steal): new epoch
                nxt = self._epochs.get(lease.name, 0) + 1
                self._epochs[lease.name] = nxt
                stored.epoch = nxt
            elif cur is not None:
                stored.epoch = cur.epoch
            self._leases[lease.name] = stored
            return True


class LeaderElector:
    """tools/leaderelection.LeaderElector reduced to the scheduler's use:
    tryAcquireOrRenew on a timer; is_leader() gates the scheduling loop."""

    def __init__(self, store: LeaseStore, identity: str,
                 lease_name: str = "kube-scheduler",
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 2.0,
                 now: Callable[[], float] = time.time,
                 on_started_leading: Optional[Callable] = None,
                 on_stopped_leading: Optional[Callable] = None):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        # client-go validates LeaseDuration > RenewDeadline; clamp to
        # the reference's 2/3 ratio so a short --lease-duration cannot
        # open a dual-leader window (peer steals at lease_duration while
        # we still think the renew deadline hasn't passed)
        self.renew_deadline = min(renew_deadline, lease_duration * 2 / 3)
        self.retry_period = retry_period
        self.now = now
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_try = 0.0
        self._last_renew = 0.0   # last SUCCESSFUL acquire/renew
        self.transport_errors = 0
        # fencing token of our newest acquisition. Deliberately NOT
        # cleared on step-down: in-flight writes must keep carrying the
        # epoch they were issued under so the hub can reject them after
        # a peer acquires a newer one.
        self.epoch = 0

    def is_leader(self) -> bool:
        return self._leading

    def _enforce_renew_deadline(self, now: float) -> None:
        """RenewDeadline exceeded: we may still hold the lease in the
        store, but we can no longer PROVE it — step down before a peer's
        clock says we expired (split-brain guard)."""
        if self._leading and now - self._last_renew > self.renew_deadline:
            logger.warning("leaderelection: renew deadline exceeded "
                           "(%.1fs), stepping down", self.renew_deadline)
            self._set_leading(False)

    def try_acquire_or_renew(self) -> bool:
        """leaderelection.go tryAcquireOrRenew: renew our own lease, or
        take an expired/vacant one. A store that cannot be reached is a
        failed renew (not leading), never an escaping exception."""
        now = self.now()
        self._enforce_renew_deadline(now)
        # the try wraps ONLY store I/O: a raising user callback in
        # _set_leading must surface as itself, not masquerade as a
        # transport failure (and flap leadership forever)
        try:
            acquired = False
            cur = self.store.get(self.lease_name)
            if cur is None or not cur.holder_identity:
                ok = self.store.update(Lease(
                    name=self.lease_name, holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now), expect_holder=None)
                acquired = ok
            elif cur.holder_identity == self.identity:
                cur.renew_time = now
                # a failed CAS means a peer stole the lease while we
                # stalled: step down immediately (split-brain guard)
                ok = self.store.update(cur, expect_holder=self.identity)
                if ok:
                    self.epoch = cur.epoch
            elif now - cur.renew_time > cur.lease_duration_seconds:
                # expired: steal it (lease_transitions counts takeovers)
                ok = self.store.update(Lease(
                    name=self.lease_name, holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now,
                    lease_transitions=cur.lease_transitions + 1),
                    expect_holder=cur.holder_identity)
                acquired = ok
            else:
                ok = False
            if acquired:
                # the store stamped our fencing epoch during the CAS;
                # read it back (a racing steal leaves a stale epoch here,
                # which is exactly what fencing then rejects). The
                # read-back gets its own guard: the CAS already
                # succeeded, so a transport blip HERE must not demote a
                # holder — it just leaves the (older, safely fenced)
                # epoch until the next renew's read.
                try:
                    got = self.store.get(self.lease_name)
                    if got is not None \
                            and got.holder_identity == self.identity:
                        self.epoch = got.epoch
                except Exception as e:  # noqa: BLE001 — transport only
                    self.transport_errors += 1
                    logger.warning("leaderelection: epoch read-back "
                                   "failed (%r); keeping prior epoch", e)
        except Exception as e:  # noqa: BLE001 — remote store transport
            # failure: an unreachable store means we cannot renew; we are
            # not leading until it answers again
            self.transport_errors += 1
            logger.warning("leaderelection: lease store unreachable "
                           "(%r); treating as not leading", e)
            ok = False
        if ok:
            self._last_renew = now
        self._set_leading(ok)
        return self._leading

    def tick(self) -> bool:
        """Rate-limited try_acquire_or_renew for the maintenance loop.
        Exception-safe: transport errors demote, they never escape."""
        now = self.now()
        if now - self._last_try < self.retry_period:
            # don't coast on a stale lease between retries
            self._enforce_renew_deadline(now)
            return self._leading
        self._last_try = now
        return self.try_acquire_or_renew()

    def release(self) -> None:
        """Step down voluntarily (leaderelection.go release): zero out the
        holder so a peer acquires without waiting for expiry. Best-effort
        over an unreachable store — local demotion always happens."""
        if not self._leading:
            return
        try:
            self.store.update(Lease(
                name=self.lease_name, holder_identity="",
                lease_duration_seconds=self.lease_duration,
                acquire_time=0.0, renew_time=0.0),
                expect_holder=self.identity)
        except Exception as e:  # noqa: BLE001 — the lease then simply
            # expires on its own; peers take over within lease_duration
            self.transport_errors += 1
            logger.warning("leaderelection: release failed (%r); lease "
                           "will expire naturally", e)
        self._set_leading(False)

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
