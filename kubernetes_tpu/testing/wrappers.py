"""Fluent pod/node builders for tests — the TPU-framework analog of the
reference's wrapper fixtures (pkg/scheduler/testing/wrappers.go:298 MakePod,
:824 MakeNode). Chain setters, finish with ``.obj()``:

    pod = (MakePod().name("p").req(cpu="500m").priority(10)
           .pod_anti_affinity("kubernetes.io/hostname", {"app": "a"})
           .obj())
    node = MakeNode().name("n1").capacity(cpu="32").taint("k", "v").obj()
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    LABEL_HOSTNAME,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSchedulingGate,
    PodSpec,
    PreferredSchedulingTerm,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)


class MakePod:
    """Fluent Pod builder (wrappers.go:298 st.MakePod())."""

    def __init__(self) -> None:
        self._pod = Pod(metadata=ObjectMeta(name="pod"), spec=PodSpec())

    def obj(self) -> Pod:
        if not self._pod.spec.containers:
            self._pod.spec.containers = [Container(name="c")]
        return self._pod

    # ---- metadata ----
    def name(self, n: str) -> "MakePod":
        self._pod.metadata.name = n
        return self

    def namespace(self, ns: str) -> "MakePod":
        self._pod.metadata.namespace = ns
        return self

    def uid(self, u: str) -> "MakePod":
        self._pod.metadata.uid = u
        return self

    def label(self, k: str, v: str) -> "MakePod":
        self._pod.metadata.labels[k] = v
        return self

    def labels(self, d: dict) -> "MakePod":
        self._pod.metadata.labels.update(d)
        return self

    # ---- spec ----
    def req(self, **resources: str) -> "MakePod":
        """Add a container with the given requests (cpu="500m", memory=...).
        Underscores in kwargs map to dashes (ephemeral_storage)."""
        reqs = {k.replace("_", "-"): v for k, v in resources.items()}
        self._pod.spec.containers.append(Container(
            name=f"c{len(self._pod.spec.containers)}",
            resources=ResourceRequirements(requests=reqs)))
        return self

    def container_image(self, image: str, **resources: str) -> "MakePod":
        self.req(**resources)
        self._pod.spec.containers[-1].image = image
        return self

    def priority(self, p: int) -> "MakePod":
        self._pod.spec.priority = p
        return self

    def node_name(self, n: str) -> "MakePod":
        self._pod.spec.node_name = n
        return self

    def scheduler_name(self, n: str) -> "MakePod":
        self._pod.spec.scheduler_name = n
        return self

    def node_selector(self, sel: dict) -> "MakePod":
        self._pod.spec.node_selector.update(sel)
        return self

    def host_port(self, port: int, proto: str = "TCP",
                  host_ip: str = "") -> "MakePod":
        if not self._pod.spec.containers:
            self._pod.spec.containers = [Container(name="c")]
        self._pod.spec.containers[-1].ports.append(ContainerPort(
            host_port=port, protocol=proto, host_ip=host_ip))
        return self

    def toleration(self, key: str, value: str = "", effect: str = "",
                   operator: str = "Equal") -> "MakePod":
        self._pod.spec.tolerations.append(Toleration(
            key=key, operator=operator, value=value, effect=effect))
        return self

    def scheduling_gate(self, name: str) -> "MakePod":
        self._pod.spec.scheduling_gates.append(PodSchedulingGate(name=name))
        return self

    def preemption_policy(self, p: str) -> "MakePod":
        self._pod.spec.preemption_policy = p
        return self

    # ---- affinity ----
    def _affinity(self) -> Affinity:
        if self._pod.spec.affinity is None:
            self._pod.spec.affinity = Affinity()
        return self._pod.spec.affinity

    def node_affinity_in(self, key: str, vals: list[str]) -> "MakePod":
        """requiredDuringScheduling In-match (wrappers.go NodeAffinityIn)."""
        a = self._affinity()
        if a.node_affinity is None:
            a.node_affinity = NodeAffinity()
        if a.node_affinity.required is None:
            a.node_affinity.required = NodeSelector(node_selector_terms=[])
        a.node_affinity.required.node_selector_terms.append(NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                key=key, operator="In", values=list(vals))]))
        return self

    def preferred_node_affinity(self, weight: int, key: str,
                                vals: list[str]) -> "MakePod":
        a = self._affinity()
        if a.node_affinity is None:
            a.node_affinity = NodeAffinity()
        a.node_affinity.preferred.append(PreferredSchedulingTerm(
            weight=weight, preference=NodeSelectorTerm(
                match_expressions=[NodeSelectorRequirement(
                    key=key, operator="In", values=list(vals))])))
        return self

    @staticmethod
    def _term(topology_key: str, match: dict | LabelSelector
              ) -> PodAffinityTerm:
        sel = (match if isinstance(match, LabelSelector)
               else LabelSelector(match_labels=dict(match)))
        return PodAffinityTerm(topology_key=topology_key,
                               label_selector=sel)

    def pod_affinity(self, topology_key: str,
                     match: dict | LabelSelector) -> "MakePod":
        a = self._affinity()
        if a.pod_affinity is None:
            a.pod_affinity = PodAffinity()
        a.pod_affinity.required.append(self._term(topology_key, match))
        return self

    def pod_anti_affinity(self, topology_key: str,
                          match: dict | LabelSelector) -> "MakePod":
        a = self._affinity()
        if a.pod_anti_affinity is None:
            a.pod_anti_affinity = PodAntiAffinity()
        a.pod_anti_affinity.required.append(self._term(topology_key, match))
        return self

    def preferred_pod_affinity(self, weight: int, topology_key: str,
                               match: dict | LabelSelector) -> "MakePod":
        a = self._affinity()
        if a.pod_affinity is None:
            a.pod_affinity = PodAffinity()
        a.pod_affinity.preferred.append(WeightedPodAffinityTerm(
            weight=weight,
            pod_affinity_term=self._term(topology_key, match)))
        return self

    def preferred_pod_anti_affinity(self, weight: int, topology_key: str,
                                    match: dict | LabelSelector) -> "MakePod":
        a = self._affinity()
        if a.pod_anti_affinity is None:
            a.pod_anti_affinity = PodAntiAffinity()
        a.pod_anti_affinity.preferred.append(WeightedPodAffinityTerm(
            weight=weight,
            pod_affinity_term=self._term(topology_key, match)))
        return self

    def spread_constraint(self, max_skew: int, topology_key: str,
                          when_unsatisfiable: str = "DoNotSchedule",
                          match: dict | None = None,
                          min_domains: int | None = None) -> "MakePod":
        self._pod.spec.topology_spread_constraints.append(
            TopologySpreadConstraint(
                max_skew=max_skew, topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=LabelSelector(match_labels=dict(match or {})),
                min_domains=min_domains))
        return self


class MakeNode:
    """Fluent Node builder (wrappers.go:824 st.MakeNode())."""

    def __init__(self) -> None:
        self._node = Node(metadata=ObjectMeta(name="node"), spec=NodeSpec(),
                          status=NodeStatus(allocatable={
                              "cpu": "32", "memory": "128Gi", "pods": "110"}))

    def obj(self) -> Node:
        # hostname label mirrors the apiserver's defaulting; tests rely on
        # hostname-keyed topology just like the reference's wrappers
        self._node.metadata.labels.setdefault(
            LABEL_HOSTNAME, self._node.metadata.name)
        return self._node

    def name(self, n: str) -> "MakeNode":
        self._node.metadata.name = n
        return self

    def label(self, k: str, v: str) -> "MakeNode":
        self._node.metadata.labels[k] = v
        return self

    def capacity(self, **resources: str) -> "MakeNode":
        self._node.status.allocatable.update(
            {k.replace("_", "-"): v for k, v in resources.items()})
        return self

    def taint(self, key: str, value: str = "",
              effect: str = "NoSchedule") -> "MakeNode":
        self._node.spec.taints.append(Taint(key=key, value=value,
                                            effect=effect))
        return self

    def unschedulable(self, v: bool = True) -> "MakeNode":
        self._node.spec.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "MakeNode":
        self._node.status.images.append(ContainerImage(
            names=[name], size_bytes=size_bytes))
        return self
