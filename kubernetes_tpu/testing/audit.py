"""Journal-replay bind audit: every pod bound exactly once, fleet-wide.

The storm-grade correctness check for scheduler scale-out (and any
other multi-writer scenario): replay the hub's journal in revision
order and track each pod's ``spec.node_name`` transitions. Exactly-once
means each pod goes unbound -> bound at most once and never changes
node while bound; "no lost pods" means every uid the caller expected
binds before the journal ends. Because the journal IS the commit record
(every bind lands there before any later revision), this audits what
the cluster actually did — not what N replicas individually believe
they did.

Works against any hub shape that serves ``list_changes``: the
in-process ``Hub``, ``ShardedHub``, a ``RemoteHub`` through the router
(which merges shards in rv order). Journal change events carry the
post-event object only (``obj``), so the replay derives transitions
from per-uid state, not from old/new pairs.
"""

from __future__ import annotations

__all__ = ["audit_bind_journal"]


def _field(obj, *path, default=None):
    """Read a dotted field off a typed object or a wire dict."""
    cur = obj
    for name in path:
        if cur is None:
            return default
        if isinstance(cur, dict):
            cur = cur.get(name)
        else:
            cur = getattr(cur, name, None)
    return cur if cur is not None else default


def audit_bind_journal(changes=None, hub=None, expected_uids=None,
                       kinds: tuple = ("pods",)) -> dict:
    """Replay bind history; return the exactly-once verdict.

    Pass ``changes`` (a ``list_changes()``-shaped payload or a bare
    change list) or ``hub`` (anything serving ``list_changes``; the
    full journal is pulled from rv 0). ``expected_uids`` (optional)
    asserts coverage: uids that never bound are reported as lost.

    Returns a report dict::

        {"ok": bool, "pods_seen": int, "binds": int,
         "double_binds": [ ... one row per violation ... ],
         "lost": [uid, ...],          # expected but never bound
         "too_old": bool,             # journal compacted under us
         "bound": {uid: node}}

    ``too_old`` flags a replay that started past the compaction
    watermark — the audit is then only as complete as the surviving
    suffix, and callers that need the full-history guarantee should
    size the journal capacity to the storm (the storms do).
    """
    too_old = False
    if changes is None:
        if hub is None:
            raise ValueError("audit_bind_journal needs changes= or hub=")
        changes = hub.list_changes(0, kinds)
    if isinstance(changes, dict):
        too_old = bool(changes.get("too_old"))
        rows = changes.get("changes") or []
    else:
        rows = list(changes)

    rows = sorted(rows, key=lambda c: _field(c, "rv", default=0))
    bound: dict[str, str] = {}
    seen: set[str] = set()
    deleted: set[str] = set()
    binds = 0
    double_binds: list[dict] = []
    for c in rows:
        if _field(c, "kind", default="pods") not in kinds:
            continue
        obj = _field(c, "obj")
        uid = _field(obj, "metadata", "uid")
        if not uid:
            continue
        seen.add(uid)
        ctype = _field(c, "type", default="")
        if ctype == "delete":
            deleted.add(uid)
            continue
        node = _field(obj, "spec", "node_name", default="") or ""
        prev = bound.get(uid)
        if node:
            if prev is None:
                if uid in deleted:
                    # resurrection would be a journal-order bug, not a
                    # bind bug; flag it as a violation all the same
                    double_binds.append(
                        {"uid": uid, "violation": "bound_after_delete",
                         "node": node,
                         "rv": _field(c, "rv", default=0)})
                    continue
                bound[uid] = node
                binds += 1
            elif node != prev:
                # the exactly-once violation: a second bind moved the
                # pod — two replicas each thought they placed it
                double_binds.append(
                    {"uid": uid, "violation": "rebound",
                     "first_node": prev, "second_node": node,
                     "rv": _field(c, "rv", default=0)})
        elif prev is not None and uid not in deleted:
            # bound -> unbound without a delete: an unbind landed over
            # a committed placement (a fence that failed to hold)
            double_binds.append(
                {"uid": uid, "violation": "unbound",
                 "node": prev, "rv": _field(c, "rv", default=0)})
            bound.pop(uid, None)

    lost = sorted(set(expected_uids or ()) - set(bound))
    return {"ok": not double_binds and not lost and not too_old,
            "pods_seen": len(seen), "binds": binds,
            "double_binds": double_binds, "lost": lost,
            "too_old": too_old, "bound": dict(bound)}
