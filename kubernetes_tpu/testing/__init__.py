"""Test fixtures: fluent wrappers + scripted fake plugins
(pkg/scheduler/testing equivalents)."""

from kubernetes_tpu.testing.audit import audit_bind_journal
from kubernetes_tpu.testing.fakes import (
    CountingHub,
    FakePermitPlugin,
    FakeReservePlugin,
    FakeScorePlugin,
    FalseFilterPlugin,
    MatchFilterPlugin,
    TrueFilterPlugin,
    fake_profile,
    fake_registry,
)
from kubernetes_tpu.testing.wrappers import MakeNode, MakePod

__all__ = [
    "audit_bind_journal",
    "CountingHub",
    "FakePermitPlugin",
    "FakeReservePlugin",
    "FakeScorePlugin",
    "FalseFilterPlugin",
    "MatchFilterPlugin",
    "TrueFilterPlugin",
    "MakeNode",
    "MakePod",
    "fake_profile",
    "fake_registry",
]
