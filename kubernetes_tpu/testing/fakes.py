"""Scripted fake plugins for framework tests — the analog of the
reference's fake plugin fixtures (pkg/scheduler/testing/framework/
fake_plugins.go:36-115: TrueFilterPlugin, FalseFilterPlugin,
MatchFilterPlugin, fake score/permit/reserve plugins).

Each fake is a HOST plugin (runs through Framework.run_host_* /
run_*_plugins), so tests can exercise the mixed host/device seam without a
device kernel. ``fake_registry()`` merges them into the in-tree registry;
``fake_profile()`` builds a SchedulerProfile enabling a chosen subset on
top of the defaults.
"""

from __future__ import annotations

from typing import Callable, Optional

from kubernetes_tpu.config.types import (
    Plugin as PluginRef,
    Plugins,
    SchedulerProfile,
    default_plugins,
)
from kubernetes_tpu.framework.interface import (
    FilterPlugin,
    PermitPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.plugins.registry import (
    PluginDescriptor,
    in_tree_registry,
)


class TrueFilterPlugin(FilterPlugin):
    """Always passes (fake_plugins.go TrueFilterPlugin)."""

    NAME = "TrueFilter"

    def filter(self, state, pod, node_info) -> Status:
        return Status()


class FalseFilterPlugin(FilterPlugin):
    """Always rejects (fake_plugins.go FalseFilterPlugin)."""

    NAME = "FalseFilter"

    def filter(self, state, pod, node_info) -> Status:
        return Status.unschedulable("FalseFilter", plugin=self.NAME)


class MatchFilterPlugin(FilterPlugin):
    """Passes only the node whose name equals the pod's name
    (fake_plugins.go MatchFilterPlugin)."""

    NAME = "MatchFilter"

    def filter(self, state, pod, node_info) -> Status:
        if node_info.node.metadata.name == pod.metadata.name:
            return Status()
        return Status.unschedulable("no match", plugin=self.NAME)


class FakeScorePlugin(ScorePlugin):
    """Scores each node with a scripted function (node_name -> float);
    default scores 0 everywhere."""

    NAME = "FakeScore"

    def __init__(self, score_fn: Optional[Callable[[str], float]] = None):
        self._fn = score_fn or (lambda name: 0.0)
        self.calls: list[str] = []

    def score(self, state, pod, node_info) -> tuple[float, Status]:
        name = node_info.node.metadata.name
        self.calls.append(name)
        return float(self._fn(name)), Status()


class FakeReservePlugin(ReservePlugin):
    """Records Reserve/Unreserve calls; optionally fails Reserve."""

    NAME = "FakeReserve"

    def __init__(self, fail: bool = False):
        self.fail = fail
        self.reserved: list[tuple[str, str]] = []
        self.unreserved: list[tuple[str, str]] = []

    def reserve(self, state, pod, node_name: str) -> Status:
        self.reserved.append((pod.metadata.name, node_name))
        if self.fail:
            return Status.unschedulable("reserve failed", plugin=self.NAME)
        return Status()

    def unreserve(self, state, pod, node_name: str) -> None:
        self.unreserved.append((pod.metadata.name, node_name))


class FakePermitPlugin(PermitPlugin):
    """Returns a scripted (Status, timeout) per pod; default allows."""

    NAME = "FakePermit"

    def __init__(self, decide: Optional[Callable[[object], tuple]] = None):
        self._decide = decide
        self.calls: list[str] = []

    def permit(self, state, pod, node_name: str):
        self.calls.append(pod.metadata.name)
        if self._decide is None:
            return Status(), 0.0
        return self._decide(pod)


_FAKES: dict[str, tuple[type, tuple[str, ...]]] = {
    TrueFilterPlugin.NAME: (TrueFilterPlugin, ("filter",)),
    FalseFilterPlugin.NAME: (FalseFilterPlugin, ("filter",)),
    MatchFilterPlugin.NAME: (MatchFilterPlugin, ("filter",)),
    FakeScorePlugin.NAME: (FakeScorePlugin, ("score",)),
    FakeReservePlugin.NAME: (FakeReservePlugin, ("reserve",)),
    FakePermitPlugin.NAME: (FakePermitPlugin, ("permit",)),
}


def fake_registry(**instances) -> dict[str, PluginDescriptor]:
    """in_tree_registry() + every fake plugin. Pass pre-built instances by
    plugin name (e.g. ``FakeScore=FakeScorePlugin(fn)``) to script them;
    unnamed fakes are default-constructed by the framework."""
    reg = in_tree_registry()
    for name, (cls, points) in _FAKES.items():
        inst = instances.get(name)
        factory = ((lambda args, i=inst: i) if inst is not None
                   else (lambda args, c=cls: c()))
        reg[name] = PluginDescriptor(name=name, points=points,
                                     factory=factory)
    return reg


def fake_profile(*enabled: str, weights: Optional[dict[str, float]] = None,
                 scheduler_name: str = "default-scheduler"
                 ) -> SchedulerProfile:
    """Default profile + the named fakes enabled at their points."""
    plugins: Plugins = default_plugins()
    weights = weights or {}
    for name in enabled:
        _, points = _FAKES[name]
        for point in points:
            getattr(plugins, point).enabled.append(
                PluginRef(name, weights.get(name, 0.0)))
    return SchedulerProfile(scheduler_name=scheduler_name, plugins=plugins)


class FakePVController:
    """The integration harness's fake PV controller
    (test/integration/util/util.go:150): watches PVCs carrying the
    selected-node annotation VolumeBinding's PreBind writes for dynamic
    provisioning, provisions a PV (capacity = request, node affinity
    pinned to the chosen node), and binds the claim — completing the
    WaitForFirstConsumer flow without a real CSI driver."""

    def __init__(self, hub):
        from kubernetes_tpu.hub import EventHandlers

        self.hub = hub
        self.provisioned: list[str] = []    # pv names, in creation order
        hub.watch_pvcs(EventHandlers(
            on_add=self._maybe_provision,
            on_update=lambda old, new: self._maybe_provision(new)))

    def _maybe_provision(self, pvc) -> None:
        from kubernetes_tpu.api.objects import (
            LABEL_HOSTNAME,
            ClaimRef,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            ObjectMeta,
            PersistentVolume,
            PersistentVolumeSpec,
        )
        from kubernetes_tpu.plugins.volume import VolumeBinding

        node = pvc.metadata.annotations.get(
            VolumeBinding.SELECTED_NODE_ANNOTATION)
        if not node or pvc.spec.volume_name:
            return
        pv_name = f"provisioned-{pvc.metadata.name}"
        if self.hub.get_pv(pv_name) is None:
            self.hub.create_pv(PersistentVolume(
                metadata=ObjectMeta(name=pv_name),
                spec=PersistentVolumeSpec(
                    capacity={"storage":
                              pvc.spec.requests.get("storage", "0")},
                    access_modes=list(pvc.spec.access_modes),
                    storage_class_name=pvc.spec.storage_class_name,
                    claim_ref=ClaimRef(namespace=pvc.metadata.namespace,
                                       name=pvc.metadata.name,
                                       uid=pvc.metadata.uid),
                    node_affinity=NodeSelector(node_selector_terms=[
                        NodeSelectorTerm(match_expressions=[
                            NodeSelectorRequirement(
                                key=LABEL_HOSTNAME, operator="In",
                                values=[node])])]))))
            self.provisioned.append(pv_name)
        bound = pvc.clone()
        bound.spec.volume_name = pv_name
        bound.status.phase = "Bound"
        self.hub.update_pvc(bound)


class CountingHub:
    """Forwarding hub wrapper counting the O(cluster) LIST reads — the
    drift sentinel's zero-LIST gates (tests/test_drift.py and the
    --fanout-smoke drift phase) both assert against it, so the
    definition of "a cluster LIST" lives in exactly one place."""

    def __init__(self, hub):
        self._hub = hub
        self.lists = 0

    def list_pods(self):
        self.lists += 1
        return self._hub.list_pods()

    def list_nodes(self):
        self.lists += 1
        return self._hub.list_nodes()

    def __getattr__(self, name):
        return getattr(self._hub, name)
