"""Shared production-drive scenario for multi-chip parity checks.

One harness drives the REAL Scheduler drain loop — auction batches
(plain pods), topology batches (hostname anti-affinity + optional zone
spread), and a preemption burst on a saturated node pool — against the
in-process hub, optionally under a ``jax.sharding.Mesh``. Both the driver
dryrun (``__graft_entry__.dryrun_multichip``) and the pytest parity suite
(tests/test_multichip.py) call THIS function, so the drain choreography
they compare can never diverge.

Determinism notes baked in: explicit uids (the process-global uid counter
would change uid-hash tie-breaks between runs) and synchronous binding
(the binder pool's hub writes land in thread-arrival order).
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


def make_node(i: int, zone: str, labels: dict | None = None,
              cpu: str = "4") -> Node:
    name = f"node-{i:04d}"
    lab = {LABEL_HOSTNAME: name, LABEL_ZONE: zone}
    lab.update(labels or {})
    return Node(metadata=ObjectMeta(name=name, uid=f"uid-n-{name}",
                                    labels=lab),
                spec=NodeSpec(),
                status=NodeStatus(allocatable={"cpu": cpu, "memory": "32Gi",
                                               "pods": "110"}))


def make_pod(name: str, cpu: str = "500m", labels: dict | None = None,
             priority: int = 0, selector: dict | None = None,
             anti_on: dict | None = None, spread: bool = False) -> Pod:
    affinity = None
    if anti_on:
        affinity = Affinity(pod_anti_affinity=PodAntiAffinity(
            required=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels=anti_on),
                topology_key=LABEL_HOSTNAME)]))
    tsc = []
    if spread:
        tsc = [TopologySpreadConstraint(
            max_skew=1, topology_key=LABEL_ZONE,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels={"tier": "spread"}))]
    return Pod(metadata=ObjectMeta(name=name, uid=f"uid-p-{name}",
                                   labels=labels or {}),
               spec=PodSpec(
                   containers=[Container(name="c",
                                         resources=ResourceRequirements(
                                             requests={"cpu": cpu,
                                                       "memory": "256Mi"}))],
                   priority=priority, node_selector=selector or {},
                   affinity=affinity, topology_spread_constraints=tsc))


def drive_production_scenario(mesh, n_nodes: int, caps: Capacities, *,
                              zones: int = 4, gold_nodes: int = 2,
                              plain: int = 8, anti: int = 4,
                              spread: int = 0, low: int = 4, high: int = 1,
                              batch_size: int = 8, drain_rounds: int = 5,
                              ) -> tuple[dict, Scheduler]:
    """Run the production drain end to end; returns ({pod: node}, sched).

    Phases: (A) ``plain`` pods — the parallel-rounds auction commit mode
    (+ ``anti``/``spread`` topology pods — the serial as-if-serial commit
    scan); (B) ``low`` 1800m fillers saturate the ``gold_nodes``-node
    'pool=gold' subset; (C) ``high`` priority-100 pods restricted to the
    pool must dry-run victims, nominate, evict, and bind — the preemption
    sweep on (optionally sharded) resident blobs."""
    hub = Hub()
    cfg = default_config()
    cfg.batch_size = batch_size
    cfg.async_binding = False
    clock = [1000.0]
    sched = Scheduler(hub, cfg, caps=caps, now=lambda: clock[0], mesh=mesh)
    for i in range(n_nodes):
        labels = {"pool": "gold"} if i < gold_nodes else None
        hub.create_node(make_node(i, zone=f"z{i % zones}", labels=labels))
    for i in range(plain):
        hub.create_pod(make_pod(f"plain-{i:03d}"))
    for i in range(anti):
        hub.create_pod(make_pod(f"anti-{i:02d}", labels={"grp": "a"},
                                anti_on={"grp": "a"}))
    for i in range(spread):
        hub.create_pod(make_pod(f"spread-{i:02d}",
                                labels={"tier": "spread"}, spread=True))
    sched.run_until_idle()
    for i in range(low):
        hub.create_pod(make_pod(f"low-{i}", cpu="1800m",
                                selector={"pool": "gold"}))
    sched.run_until_idle()
    for i in range(high):
        hub.create_pod(make_pod(f"high-{i}", cpu="1800m", priority=100,
                                selector={"pool": "gold"}))
    for _ in range(drain_rounds):
        sched.run_until_idle()
        clock[0] += 3.0
        sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    return {p.metadata.name: p.spec.node_name
            for p in hub.list_pods()}, sched
