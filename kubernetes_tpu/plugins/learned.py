"""LearnedScore: the profile-gated host manager for the fused MLP score
term.

Like every other device score plugin, the per-node math lives in an ops
kernel (ops/learned.py) fused into the one Filter/Score launch — this
class is only the HOST seam: it owns the checkpoint watcher (mtime
hot-reload, polled by the scheduler at snapshot-sync time), converts a
freshly loaded numpy stack to device arrays once per reload (params
then ride every launch without re-upload — same-architecture swaps
never recompile), and surfaces /debug/scorer + metrics state.

Off by default: the plugin is NOT in DEFAULT_MULTI_POINT; a profile
opts in with

    plugins:  {score: {enabled: [{name: LearnedScore, weight: 1}]}}
    plugin_config:
      LearnedScore: {checkpoint_path: /path/to/scorer.json}

With no loadable checkpoint the manager serves params=None and the
launch compiles the learned kernel out — identical to the plugin being
disabled. A checkpoint that loads but produces NaNs is contained by the
launch guard + device->host fallback ladder (that batch schedules on
hand-tuned weights); a corrupt overwrite of a good checkpoint keeps the
last good params and counts the error.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger("kubernetes_tpu.learned")


class LearnedScore:
    """Host manager for the fused learned score term (device_score
    descriptor; see ops/learned.py for the kernel)."""

    NAME = "LearnedScore"

    def __init__(self, args: Optional[dict] = None):
        args = args or {}
        self.checkpoint_path = args.get("checkpoint_path")
        self._watcher = None
        if self.checkpoint_path:
            from kubernetes_tpu.learn.checkpoint import CheckpointWatcher

            self._watcher = CheckpointWatcher(self.checkpoint_path)
        self._device_params = None
        self.reloads = 0          # param swaps AFTER the initial load

    def name(self) -> str:
        return self.NAME

    def maybe_reload(self) -> bool:
        """mtime-poll the checkpoint (one stat when unchanged); on a
        fresh load push the params to device. Returns True when the
        served params changed."""
        w = self._watcher
        if w is None:
            return False
        if not w.poll():
            return False
        import jax.numpy as jnp

        had = self._device_params is not None
        self._device_params = tuple(
            (jnp.asarray(wt), jnp.asarray(b)) for wt, b in w.params)
        if had:
            self.reloads += 1
        # generation 0 = a manual publish (learn train / identity);
        # >0 = the learn-loop's gated promotion — the fleet scrape
        # distinguishes the two via the reloads counter's label
        logger.info("learned scorer checkpoint %s loaded (version %s, "
                    "generation %s, fingerprint %s)",
                    self.checkpoint_path, self.version, self.generation,
                    self.fingerprint)
        return True

    def params(self):
        """The device params pytree, or None when no checkpoint has
        ever loaded (the launch then compiles the kernel out)."""
        return self._device_params

    @property
    def version(self) -> int:
        w = self._watcher
        if w is None or not w.meta:
            return 0
        try:
            return int(w.meta.get("version", 0))
        except (TypeError, ValueError):
            return 0

    @property
    def generation(self) -> int:
        """The learn-loop generation that produced the active
        checkpoint; 0 for manual publishes (learn train / identity)."""
        w = self._watcher
        if w is None or not w.meta:
            return 0
        try:
            return int(w.meta.get("generation", 0))
        except (TypeError, ValueError):
            return 0

    @property
    def fingerprint(self) -> str:
        w = self._watcher
        return (w.meta.get("fingerprint", "") if w is not None else "")

    def stats(self) -> dict:
        """/debug/scorer payload for one profile: checkpoint identity,
        the learn-loop generation + regret summaries stamped by the
        promotion gate, reload/error counts."""
        w = self._watcher
        out = {
            "enabled": True,
            "checkpoint_path": self.checkpoint_path,
            "loaded": self._device_params is not None,
            "version": self.version,
            "generation": self.generation,
            "fingerprint": self.fingerprint,
            "reloads": self.reloads,
        }
        if w is not None:
            out.update(loads=w.loads, load_errors=w.load_errors,
                       last_error=w.last_error)
            if w.meta:
                meta = {k: v for k, v in w.meta.items()
                        if k not in ("fingerprint",)}
                out["meta"] = meta
                # the loop's regret view: training-set regret and the
                # gate's holdout regret ride the promoted meta
                for k in ("regret", "holdout_regret", "gate_wins",
                          "promoted", "rolled_back_from"):
                    if k in meta:
                        out[k] = meta[k]
        return out
