"""DynamicResources: the DRA scheduler plugin, TPU-native host edition.

From-scratch equivalent of the reference's accelerator-scheduling path
(plugins/dynamicresources/dynamicresources.go:105-888 + the structured
allocator): pods reference ResourceClaims; DRA drivers publish per-node
device inventories as ResourceSlices; the plugin

- PreFilter: resolve the pod's claims (missing claim => unresolvable;
  no claims => Skip), build the free-device view per node from every
  other claim's allocation (API truth + the assume overlay),
- Filter: a node fits iff every unallocated claim can be satisfied from
  that node's remaining devices, and every ALLOCATED claim is pinned to
  its allocation's node,
- Reserve: pick concrete devices on the chosen node and ASSUME the
  allocation (assume overlay — the scheduler-side AssumeCache the
  reference keeps for claims), Unreserve reverts,
- PreBind: write the allocation + reservedFor to the API (hub).

Restart safety is API-truth-based like everything else in this build: a
restarted scheduler rebuilds its view from claim statuses, so allocations
survive replay and allocated devices never double-book.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.objects import (
    AllocationResult,
    DeviceAllocationResult,
    Pod,
    ResourceClaim,
)
from kubernetes_tpu.framework.interface import (
    FilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    Status,
)


def dra_serial_keys(hub, pod: Pod) -> set[str]:
    """Host-serial conflict domains: two pods referencing the SAME claim
    must not share a batch (the first one's assume — allocation or
    reservedFor append — changes what the second must see).

    Pods with DISTINCT claims deliberately DO share batches even when
    their claims compete for one device class: reserve() re-walks the
    free-device view through the assume overlay sequentially at commit
    time and fails cleanly ("devices vanished") into the requeue path, so
    a same-batch capacity race costs one retry, never a double-booking.
    Serializing per device class instead was measured at ~50x throughput
    loss (one claim pod per launch) on DRA steady-state."""
    keys: set[str] = set()
    for ref in pod.spec.resource_claims:
        claim = hub.get_resource_claim(pod.metadata.namespace,
                                       ref.resource_claim_name)
        if claim is None:
            continue
        keys.add(f"draclaim:{claim.key()}")
    return keys


def release_pod_claims(hub, pod: Pod) -> None:
    """The slice of the reference's resourceclaim controller the scheduler
    build needs: a deleted pod leaves its claims' reservedFor. The
    ALLOCATION persists — a standalone claim owns its devices until the
    claim itself is deleted (that is how users hand a device from pod to
    pod); freeing capacity means deleting the claim, whose event requeues
    waiting DRA pods."""
    for ref in pod.spec.resource_claims:
        claim = hub.get_resource_claim(pod.metadata.namespace,
                                       ref.resource_claim_name)
        if claim is None \
                or pod.metadata.uid not in claim.status.reserved_for:
            continue
        new = claim.clone()
        new.status.reserved_for.remove(pod.metadata.uid)
        hub.update_resource_claim(new)


@dataclass
class ClaimAssumeCache:
    """Assumed claim allocations ahead of the API write."""

    allocations: dict[str, ResourceClaim] = field(default_factory=dict)

    def assume(self, claim: ResourceClaim) -> None:
        self.allocations[claim.key()] = claim

    def restore(self, key: str) -> None:
        self.allocations.pop(key, None)

    def get(self, key: str) -> Optional[ResourceClaim]:
        return self.allocations.get(key)


class DynamicResources(PreFilterPlugin, FilterPlugin, ReservePlugin,
                       PreBindPlugin):
    NAME = "DynamicResources"
    STATE_KEY = "DynamicResources/claims"
    ASSUMED_KEY = "DynamicResources/assumed"

    def __init__(self, hub):
        self.hub = hub
        self.assume = ClaimAssumeCache()

    @staticmethod
    def applies(pod: Pod) -> bool:
        return bool(pod.spec.resource_claims)

    # --- views through the assume overlay ---

    def _claim(self, ns: str, name: str) -> Optional[ResourceClaim]:
        c = self.hub.get_resource_claim(ns, name)
        if c is None:
            return None
        assumed = self.assume.get(c.key())
        return assumed if assumed is not None else c

    def _pod_claims(self, pod: Pod):
        for ref in pod.spec.resource_claims:
            yield ref, self._claim(pod.metadata.namespace,
                                   ref.resource_claim_name)

    def _used_devices(self, exclude_keys: set[str]) -> set[tuple]:
        """(driver, pool, device) triples allocated by ANY claim (API truth
        overlaid with assumed allocations), except the excluded claims."""
        used: set[tuple] = set()
        seen: set[str] = set()
        for claim in list(self.assume.allocations.values()) \
                + self.hub.list_resource_claims():
            if claim.key() in seen:
                continue
            seen.add(claim.key())
            if claim.key() in exclude_keys:
                continue
            alloc = claim.status.allocation
            if alloc is None:
                continue
            for d in alloc.devices:
                used.add((d.driver, d.pool, d.device))
        return used

    def _free_by_node(self, exclude_keys: set[str]) -> dict[str, list]:
        """node -> [(driver, pool, device, device_class)] still free."""
        used = self._used_devices(exclude_keys)
        free: dict[str, list] = {}
        for sl in self.hub.list_resource_slices():
            for dev in sl.devices:
                key = (sl.driver, sl.pool, dev.name)
                if key in used:
                    continue
                free.setdefault(sl.node_name, []).append(
                    (sl.driver, sl.pool, dev.name, dev.device_class_name))
        return free

    @staticmethod
    def _satisfiable(claim: ResourceClaim, free_devs: list) -> bool:
        pool = list(free_devs)
        for req in claim.spec.device_requests:
            need = req.count
            for i in range(len(pool) - 1, -1, -1):
                if need == 0:
                    break
                if pool[i][3] == req.device_class_name:
                    pool.pop(i)
                    need -= 1
            if need > 0:
                return False
        return True

    # --- extension points ---

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        if not pod.spec.resource_claims:
            return Status.skip()
        claims = []
        for ref, claim in self._pod_claims(pod):
            if claim is None:
                return Status.unschedulable(
                    f'resourceclaim "{ref.resource_claim_name}" not found',
                    plugin=self.NAME, resolvable=False)
            claims.append(claim)
        state.write(self.STATE_KEY, claims)
        # exclude only the pod's UNALLOCATED claims: an allocated claim's
        # devices are taken no matter who reads the view (excluding it
        # would let a sibling claim double-book them)
        exclude = {c.key() for c in claims
                   if c.status.allocation is None}
        state.write(self.STATE_KEY + "/free", self._free_by_node(exclude))
        return Status()

    def filter(self, state, pod: Pod, node_info) -> Status:
        claims = state.read(self.STATE_KEY) or []
        free = state.read(self.STATE_KEY + "/free") or {}
        node_name = node_info.node.metadata.name
        for claim in claims:
            alloc = claim.status.allocation
            if alloc is not None:
                if alloc.node_name and alloc.node_name != node_name:
                    return Status.unschedulable(
                        "claim already allocated on another node",
                        plugin=self.NAME)
                continue
            if not self._satisfiable(claim, free.get(node_name, [])):
                return Status.unschedulable(
                    "cannot allocate all claims", plugin=self.NAME)
        return Status()

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        assumed_keys = []
        claims = []
        for ref, c in self._pod_claims(pod):
            if c is None:
                return Status.unschedulable(
                    f'resourceclaim "{ref.resource_claim_name}" '
                    "disappeared", plugin=self.NAME)
            claims.append(c)
        exclude = {c.key() for c in claims
                   if c.status.allocation is None}
        free = self._free_by_node(exclude).get(node_name, [])
        for claim in claims:
            if claim.status.allocation is not None:
                # already allocated: record this pod as a consumer
                if pod.metadata.uid not in claim.status.reserved_for:
                    new = claim.clone()
                    new.status.reserved_for.append(pod.metadata.uid)
                    self.assume.assume(new)
                    assumed_keys.append(new.key())
                continue
            picked: list[DeviceAllocationResult] = []
            pool = list(free)
            ok = True
            for req in claim.spec.device_requests:
                for _ in range(req.count):
                    idx = next((i for i, d in enumerate(pool)
                                if d[3] == req.device_class_name), None)
                    if idx is None:
                        ok = False
                        break
                    drv, pl, dev, _cls = pool.pop(idx)
                    picked.append(DeviceAllocationResult(
                        request=req.name, driver=drv, pool=pl, device=dev))
                if not ok:
                    break
            if not ok:
                for k in assumed_keys:
                    self.assume.restore(k)
                return Status.unschedulable(
                    "devices vanished before reserve", plugin=self.NAME)
            free = pool
            new = claim.clone()
            new.status.allocation = AllocationResult(
                node_name=node_name, devices=picked)
            if pod.metadata.uid not in new.status.reserved_for:
                new.status.reserved_for.append(pod.metadata.uid)
            self.assume.assume(new)
            assumed_keys.append(new.key())
        state.write(self.ASSUMED_KEY, assumed_keys)
        return Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        for key in state.read(self.ASSUMED_KEY) or []:
            self.assume.restore(key)

    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        for key in state.read(self.ASSUMED_KEY) or []:
            assumed = self.assume.get(key)
            if assumed is None:
                continue
            ns, name = key.split("/", 1)
            stored = self.hub.get_resource_claim(ns, name)
            if stored is None:
                return Status.error(f"resourceclaim {key} disappeared",
                                    plugin=self.NAME)
            try:
                new = stored.clone()
                if assumed.status.allocation is not None:
                    new.status.allocation = assumed.status.allocation
                merged = list(new.status.reserved_for)
                for uid in assumed.status.reserved_for:
                    if uid not in merged:
                        merged.append(uid)
                new.status.reserved_for = merged
                self.hub.update_resource_claim(new)
            except Exception as e:  # noqa: BLE001 — surfaced as Status
                return Status.error(str(e), plugin=self.NAME)
            self.assume.restore(key)
        return Status()
