"""DynamicResources: the DRA scheduler plugin, TPU-native host edition.

From-scratch equivalent of the reference's accelerator-scheduling path
(plugins/dynamicresources/dynamicresources.go:105-888 + the structured
allocator under staging/src/k8s.io/dynamic-resource-allocation): pods
reference ResourceClaims; DRA drivers publish per-node device inventories
as ResourceSlices; the plugin

- PreFilter: resolve the pod's claims — direct names or per-pod claims
  generated from ResourceClaimTemplates (pod.status.resourceClaimStatuses
  written by the ResourceClaimController below) — missing claim =>
  unresolvable; no claims => Skip; build the free-device view per node
  from the incremental allocated-device ledger + the assume overlay,
- Filter: a node fits iff every unallocated claim can be ALLOCATED from
  that node's remaining devices (structured parameters: per-request CEL
  selectors + DeviceClass selectors, ExactCount/All modes, firstAvailable
  alternatives, adminAccess, matchAttribute constraints), and every
  already-allocated claim is pinned to its allocation's node.

  The HOT PATH of that verdict now runs on device: DeviceAllocatorView
  mirrors the slice inventory into dense tensors with precompiled CEL
  verdict bitmasks, and the scheduler fuses claim feasibility for the
  whole batch into the Filter/Score launch (ops/dra.py). Pods routed
  that way skip this plugin's host Filter (applies() -> False); pods
  whose claims fall outside the device-expressible subset — constraints,
  firstAvailable, adminAccess, unparseable selectors — keep the host
  path below, which is also the wholesale fallback when a device launch
  faults. The serial allocator remains the single source of truth at
  Reserve/PreBind (commit-time bookkeeping), so device and host picks
  can never diverge on what reaches the API,
- Reserve: run the same allocator on the chosen node and ASSUME the
  allocation (assume overlay — the scheduler-side AssumeCache the
  reference keeps for claims), Unreserve reverts,
- PreBind: write the allocation + reservedFor to the API (hub).

Restart safety is API-truth-based like everything else in this build: a
restarted scheduler rebuilds its view from claim statuses, so allocations
survive replay and allocated devices never double-book.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from kubernetes_tpu.api.objects import (
    ALLOCATION_MODE_ALL,
    ALLOCATION_MODE_EXACT,
    AllocationResult,
    DeviceAllocationResult,
    ObjectMeta,
    Pod,
    ResourceClaim,
)
from kubernetes_tpu.hub import Unavailable
from kubernetes_tpu.framework.interface import (
    FilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    Status,
)
from kubernetes_tpu.ops.dra import (
    MAX_SELECTORS,
    PIN_ANY,
    PIN_NONE,
    SELBIT_WORDS,
    DraBatch,
)
from kubernetes_tpu.utils.cel import CelDevice, CelError, evaluate
from kubernetes_tpu.utils.cel import _parse as _cel_parse


def claim_name_for(pod: Pod, ref) -> str:
    """Resolve a pod.spec.resourceClaims entry to a claim NAME: direct
    reference, or the controller-generated name for a template reference
    (pod.status.resourceClaimStatuses, falling back to the deterministic
    '<pod>-<ref>' convention the controller uses)."""
    if ref.resource_claim_name:
        return ref.resource_claim_name
    if ref.resource_claim_template_name:
        return (pod.status.resource_claim_statuses.get(ref.name)
                or f"{pod.metadata.name}-{ref.name}")
    return ref.name


def dra_serial_keys(hub, pod: Pod) -> set[str]:
    """Host-serial conflict domains: two pods referencing the SAME claim
    must not share a batch (the first one's assume — allocation or
    reservedFor append — changes what the second must see).

    Pods with DISTINCT claims deliberately DO share batches even when
    their claims compete for one device class: reserve() re-walks the
    free-device view through the assume overlay sequentially at commit
    time and fails cleanly ("devices vanished") into the requeue path, so
    a same-batch capacity race costs one retry, never a double-booking.
    Serializing per device class instead was measured at ~50x throughput
    loss (one claim pod per launch) on DRA steady-state."""
    keys: set[str] = set()
    for ref in pod.spec.resource_claims:
        claim = hub.get_resource_claim(pod.metadata.namespace,
                                       claim_name_for(pod, ref))
        if claim is None:
            continue
        keys.add(f"draclaim:{claim.key()}")
    return keys


def release_pod_claims(hub, pod: Pod) -> None:
    """The slice of the reference's resourceclaim controller the scheduler
    build needs: a deleted pod leaves its claims' reservedFor. The
    ALLOCATION persists — a standalone claim owns its devices until the
    claim itself is deleted (that is how users hand a device from pod to
    pod); freeing capacity means deleting the claim, whose event requeues
    waiting DRA pods."""
    for ref in pod.spec.resource_claims:
        claim = hub.get_resource_claim(pod.metadata.namespace,
                                       claim_name_for(pod, ref))
        if claim is None \
                or pod.metadata.uid not in claim.status.reserved_for:
            continue
        new = claim.clone()
        new.status.reserved_for.remove(pod.metadata.uid)
        hub.update_resource_claim(new)


class ResourceClaimController:
    """The resourceclaim controller slice this build needs (the reference
    runs the full version in kube-controller-manager,
    pkg/controller/resourceclaim): watches pods, stamps a per-pod
    ResourceClaim out of each referenced ResourceClaimTemplate under the
    deterministic name '<pod>-<ref>', records the generated names in
    pod.status.resourceClaimStatuses, and deletes the owned claims when
    the pod goes away (template-generated claims die with their pod;
    directly-referenced claims persist)."""

    def __init__(self, hub):
        from kubernetes_tpu.hub import EventHandlers

        self.hub = hub
        # pods-by-template index: (namespace, template name) -> {uid: Pod}.
        # Template stamping is O(changes): a template arriving re-stamps
        # only the pods that reference it, never the whole cluster (the
        # old `for pod in hub.list_pods()` scan was O(cluster) per
        # template event). The lock covers hub dispatch threads racing
        # pod adds against template adds.
        self._index_lock = threading.Lock()
        self._tmpl_index: dict[tuple[str, str], dict[str, Pod]] = {}
        hub.watch_pods(EventHandlers(on_add=self._on_pod_add,
                                     on_delete=self._on_pod_delete))
        # a pod can reference a template created AFTER it (the reference
        # controller retries via its workqueue): re-stamp waiting pods
        # when their template appears
        hub.watch_resource_claim_templates(EventHandlers(
            on_add=self._on_template_add))

    def _index_pod(self, pod: Pod) -> None:
        with self._index_lock:
            for ref in pod.spec.resource_claims:
                if ref.resource_claim_template_name:
                    key = (pod.metadata.namespace,
                           ref.resource_claim_template_name)
                    self._tmpl_index.setdefault(key, {})[
                        pod.metadata.uid] = pod

    def _unindex_pod(self, pod: Pod) -> None:
        with self._index_lock:
            for ref in pod.spec.resource_claims:
                if ref.resource_claim_template_name:
                    key = (pod.metadata.namespace,
                           ref.resource_claim_template_name)
                    waiting = self._tmpl_index.get(key)
                    if waiting is not None:
                        waiting.pop(pod.metadata.uid, None)
                        if not waiting:
                            del self._tmpl_index[key]

    def _on_template_add(self, tmpl) -> None:
        key = (tmpl.metadata.namespace, tmpl.metadata.name)
        with self._index_lock:
            waiting = list(self._tmpl_index.get(key, {}).values())
        for pod in waiting:
            self._stamp(pod)

    def _on_pod_add(self, pod: Pod) -> None:
        self._index_pod(pod)
        self._stamp(pod)

    def _stamp(self, pod: Pod) -> None:
        import copy

        statuses: dict[str, str] = {}
        for ref in pod.spec.resource_claims:
            if not ref.resource_claim_template_name:
                continue
            name = f"{pod.metadata.name}-{ref.name}"
            tmpl = self.hub.get_resource_claim_template(
                pod.metadata.namespace, ref.resource_claim_template_name)
            if tmpl is None:
                continue    # the template watch re-stamps on its arrival
            if self.hub.get_resource_claim(pod.metadata.namespace,
                                           name) is None:
                self.hub.create_resource_claim(ResourceClaim(
                    metadata=ObjectMeta(name=name,
                                        namespace=pod.metadata.namespace),
                    spec=copy.deepcopy(tmpl.spec)))
            statuses[ref.name] = name
        if statuses and pod.status.resource_claim_statuses != statuses:
            self.hub.set_pod_claim_statuses(pod.metadata.uid, statuses)

    def _on_pod_delete(self, pod: Pod) -> None:
        self._unindex_pod(pod)
        for ref in pod.spec.resource_claims:
            if not ref.resource_claim_template_name:
                continue
            name = (pod.status.resource_claim_statuses.get(ref.name)
                    or f"{pod.metadata.name}-{ref.name}")
            claim = self.hub.get_resource_claim(pod.metadata.namespace,
                                                name)
            if claim is not None:
                self.hub.delete_resource_claim(claim.metadata.uid)


class DeviceAllocatorView:
    """Dense device-inventory mirror + precompiled CEL selector masks:
    the host half of the batched device allocator (ops/dra.py).

    What it keeps, and when it pays:

    - a per-node device table derived from the plugin's slice ledger
      (``_node_bits``): per device, one uint32[SELBIT_WORDS] verdict
      bitmask over every registered selector. Recomputed only for DIRTY
      nodes (slice add/remove) or when a NEW selector registers — the
      steady state does zero CEL evaluation per cycle;
    - the selector registry (``_sel_bit``): expression -> bit. Entries
      are ("cel", expression) for CEL selectors and ("class", name) for
      the legacy direct device_class_name match. Selectors register
      lazily the first time a claim referencing them is packed —
      effectively at watch time, since claims/classes arrive by watch.
      A selector that fails to PARSE routes its claims to the host path
      (and surfaces the same CELSelectorError Event the host path
      records); per-device evaluation errors count as no-match with the
      Event preserved, exactly like the host's _selector_accepts;
    - the resident [N, D] / [N, D, W] device arrays pushed to HBM,
      re-assembled only when a node's bits, the mirror's row assignment,
      or the node capacity changed; the [N, D] in-use mask re-packs per
      cycle from the allocated-device ledger + the assume overlay.

    Thread model: build() runs on the scheduling-loop thread;
    invalidate_node() may arrive from hub dispatch threads. ``_lock``
    (the view's own) orders them; plugin._ledger_lock is only ever taken
    INSIDE it (view -> ledger), never the other way around.
    """

    MAX_REQS = 32        # flattened requests per pod beyond -> host path

    def __init__(self, plugin: "DynamicResources"):
        self.plugin = plugin
        self._lock = threading.Lock()
        self._sel_bit: dict[tuple, int] = {}
        self._sel_bad: set[tuple] = set()        # unparseable expressions
        self._eval_err: dict[tuple, Exception] = {}  # first eval error
        # node -> (entries, bits[d, W]); entries mirror _devices_on(node)
        self._node_bits: dict[str, tuple[list, np.ndarray]] = {}
        self._dirty: set[str] = set()            # nodes needing rebits
        self._triple_loc: dict[tuple, tuple[str, int]] = {}
        self._node_triples: dict[str, list[tuple]] = {}
        self._row_cache: dict[str, int] = {}     # node -> last packed row
        self._d_cap = 8                          # pow2 device bucket
        self._push: Optional[tuple] = None       # (valid, selbits) jnp
        self._push_n_cap = 0
        self.stats = {"selectors_compiled": 0, "host_fallback_pods": 0,
                      "device_pods": 0, "inventory_rebuilds": 0}

    # ------------- slice-watch maintenance -------------

    def invalidate_node(self, node_name: str) -> None:
        """A ResourceSlice on ``node_name`` changed: its verdict bits and
        slot map are stale. Called by the plugin's slice handlers AFTER
        they release the ledger lock."""
        with self._lock:
            self._dirty.add(node_name)
            self._push = None

    # ------------- selector registry -------------

    def _bit_for(self, key: tuple, source: tuple[str, str]
                 ) -> Optional[int]:
        """Bit index for one selector key, registering it (and dirtying
        every node's verdict table) on first sight. None = outside the
        compilable subset (parse failure or registry full) — the caller
        routes the claim to the host path."""
        if key in self._sel_bad:
            # surface the parse error for THIS source too (the plugin
            # dedups per (source, expression), like the host path)
            self.plugin._record_cel_error(
                source, key[1], self._eval_err.get(
                    key, CelError("unparseable selector")))
            return None
        bit = self._sel_bit.get(key)
        if bit is None:
            if len(self._sel_bit) >= MAX_SELECTORS:
                return None
            if key[0] == "cel":
                try:
                    _cel_parse(key[1])
                except CelError as e:
                    self._sel_bad.add(key)
                    self._eval_err[key] = e
                    self.plugin._record_cel_error(source, key[1], e)
                    return None
            bit = self._sel_bit[key] = len(self._sel_bit)
            self.stats["selectors_compiled"] += 1
            self._dirty.update(self._node_bits)
            self._push = None
        err = self._eval_err.get(key)
        if err is not None:
            # an expression that errored on some device: every source
            # referencing it gets its own (deduped) Event, host-parity
            self.plugin._record_cel_error(source, key[1], err)
        return bit

    def _verdict(self, key: tuple, driver: str, dev) -> bool:
        """One selector against one device — the precompile-time analog
        of the host _selector_accepts (same evaluate(), same CelError =
        no-match semantics; the Event is recorded once per expression
        here and re-attributed per source by _bit_for)."""
        if key[0] == "class":
            return dev.device_class_name == key[1]
        try:
            return evaluate(key[1],
                            CelDevice(driver, dev.attributes, dev.capacity))
        except CelError as e:
            self._eval_err.setdefault(key, e)
            return False

    # ------------- inventory tensors -------------

    def _rebuild_node(self, node: str) -> None:
        entries = self.plugin._devices_on(node)
        for t in self._node_triples.pop(node, ()):
            self._triple_loc.pop(t, None)
        if not entries:
            self._node_bits.pop(node, None)
            self._row_cache.pop(node, None)
            return
        while len(entries) > self._d_cap:
            self._d_cap *= 2
        bits = np.zeros((len(entries), SELBIT_WORDS), np.uint32)
        for key, bit in self._sel_bit.items():
            w, m = bit // 32, np.uint32(1 << (bit % 32))
            for di, (drv, _pool, dev) in enumerate(entries):
                if self._verdict(key, drv, dev):
                    bits[di, w] |= m
        self._node_bits[node] = (entries, bits)
        triples = [(drv, pool, dev.name)
                   for (drv, pool, dev) in entries]
        self._node_triples[node] = triples
        for slot, t in enumerate(triples):
            self._triple_loc[t] = (node, slot)

    def _ensure_inventory(self, row_of: Callable[[str], int], n_cap: int
                          ) -> tuple:
        """Refresh dirty nodes' verdict bits and (if anything moved)
        re-assemble + re-push the resident [N, D(, W)] arrays."""
        import jax.numpy as jnp

        for node in sorted(self._dirty):
            self._rebuild_node(node)
        self._dirty.clear()
        moved = any(row_of(node) != self._row_cache.get(node, -3)
                    for node in self._node_bits)
        if self._push is not None and not moved \
                and self._push_n_cap == n_cap:
            return self._push
        self.stats["inventory_rebuilds"] += 1
        valid = np.zeros((n_cap, self._d_cap), bool)
        selbits = np.zeros((n_cap, self._d_cap, SELBIT_WORDS), np.uint32)
        for node, (entries, bits) in self._node_bits.items():
            row = row_of(node)
            self._row_cache[node] = row
            if row < 0 or row >= n_cap:
                continue
            k = len(entries)
            valid[row, :k] = True
            selbits[row, :k] = bits
        self._push = (jnp.asarray(valid), jnp.asarray(selbits))
        self._push_n_cap = n_cap
        return self._push

    def _in_use_array(self, n_cap: int) -> np.ndarray:
        """[N, D] bool from the allocated-device ledger + assume overlay
        (the batch-start view every pod's host pre_filter used to
        compute; same-batch capacity races resolve at Reserve exactly as
        before)."""
        arr = np.zeros((n_cap, self._d_cap), bool)
        for t in self.plugin._in_use_view(set()):
            loc = self._triple_loc.get(t)
            if loc is None:
                continue
            row = self._row_cache.get(loc[0], -1)
            if 0 <= row < n_cap:
                arr[row, loc[1]] = True
        return arr

    # ------------- claim compilation -------------

    def _claim_reqs(self, claim: ResourceClaim
                    ) -> Optional[list[tuple[np.ndarray, int, bool]]]:
        """Flatten one unallocated claim into (mask words, count, all)
        request rows, or None when the claim is outside the
        device-expressible subset (constraints, firstAvailable,
        adminAccess, non-positive counts, uncompilable selectors)."""
        if claim.spec.constraints:
            return None
        out = []
        for req in claim.spec.device_requests:
            if req.first_available or getattr(req, "admin_access", False):
                return None
            if req.allocation_mode not in (ALLOCATION_MODE_EXACT,
                                           ALLOCATION_MODE_ALL):
                return None
            if req.allocation_mode == ALLOCATION_MODE_EXACT \
                    and req.count <= 0:
                return None
            bits: list[int] = []
            if req.device_class_name:
                dc = self.plugin.hub.get_device_class(req.device_class_name)
                if dc is None:
                    b = self._bit_for(("class", req.device_class_name),
                                      ("DeviceClass", req.device_class_name))
                    if b is None:
                        return None
                    bits.append(b)
                else:
                    for sel in dc.selectors:
                        b = self._bit_for(
                            ("cel", sel.cel_expression),
                            ("DeviceClass", req.device_class_name))
                        if b is None:
                            return None
                        bits.append(b)
            for sel in req.selectors:
                b = self._bit_for(("cel", sel.cel_expression),
                                  ("ResourceClaim", claim.key()))
                if b is None:
                    return None
                bits.append(b)
            words = np.zeros((SELBIT_WORDS,), np.uint32)
            for b in bits:
                words[b // 32] |= np.uint32(1 << (b % 32))
            is_all = req.allocation_mode == ALLOCATION_MODE_ALL
            out.append((words, 0 if is_all else req.count, is_all))
        return out

    def _pod_item(self, pod: Pod, row_of: Callable[[str], int]
                  ) -> Optional[tuple[list, int]]:
        """(flattened request rows, pinned row) for one pod, or None when
        any claim is missing or inexpressible (host path)."""
        pinned = PIN_ANY
        reqs: list = []
        for _ref, claim in self.plugin._pod_claims(pod):
            if claim is None:
                return None
            alloc = claim.status.allocation
            if alloc is not None:
                if alloc.node_name:
                    row = row_of(alloc.node_name)
                    if row < 0 or pinned not in (PIN_ANY, row):
                        pinned = PIN_NONE
                    else:
                        pinned = row
                continue
            creqs = self._claim_reqs(claim)
            if creqs is None:
                return None
            reqs.extend(creqs)
        if len(reqs) > self.MAX_REQS:
            return None
        return reqs, pinned

    # ------------- the per-dispatch build -------------

    def build(self, pods: list[Pod], row_of: Callable[[str], int],
              n_cap: int, b_cap: int
              ) -> tuple[Optional[DraBatch], dict]:
        """Pack one batch's DRA tensors. Returns (DraBatch | None, stats)
        — None when no pod in the batch is device-evaluable. Also
        refreshes the plugin's device-routing set: routed pods skip the
        host DynamicResources filter (applies() -> False) because the
        fused launch carries their verdict."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        stats = {"compile_s": 0.0, "routed": 0, "fallback": 0}
        with self._lock:
            items = []
            routed: set[str] = set()
            for b, pod in enumerate(pods):
                if not pod.spec.resource_claims:
                    continue
                item = self._pod_item(pod, row_of)
                if item is None:
                    stats["fallback"] += 1
                    continue
                items.append((b, item[0], item[1]))
                routed.add(pod.metadata.uid)
            self.plugin._device_routed = frozenset(routed)
            stats["routed"] = len(items)
            self.stats["device_pods"] += len(items)
            self.stats["host_fallback_pods"] += stats["fallback"]
            if not items:
                return None, stats
            t_c0 = time.perf_counter()
            dev_valid, dev_selbits = self._ensure_inventory(row_of, n_cap)
            stats["compile_s"] = time.perf_counter() - t_c0
            in_use = self._in_use_array(n_cap)
            q_need = max(1, max(len(reqs) for _b, reqs, _p in items))
            q_cap = 1
            while q_cap < q_need:
                q_cap *= 2
            req_mask = np.zeros((b_cap, q_cap, SELBIT_WORDS), np.uint32)
            req_count = np.zeros((b_cap, q_cap), np.int32)
            req_all = np.zeros((b_cap, q_cap), bool)
            pinned = np.full((b_cap,), PIN_ANY, np.int32)
            active = np.zeros((b_cap,), bool)
            for b, reqs, pin in items:
                active[b] = True
                pinned[b] = pin
                for q, (words, cnt, is_all) in enumerate(reqs):
                    req_mask[b, q] = words
                    req_count[b, q] = cnt
                    req_all[b, q] = is_all
            batch = DraBatch(
                dev_valid=dev_valid, dev_selbits=dev_selbits,
                dev_in_use=jnp.asarray(in_use),
                req_mask=jnp.asarray(req_mask),
                req_count=jnp.asarray(req_count),
                req_all=jnp.asarray(req_all),
                pinned=jnp.asarray(pinned),
                active=jnp.asarray(active))
            stats["build_s"] = time.perf_counter() - t0
            return batch, stats


@dataclass
class ClaimAssumeCache:
    """Assumed claim allocations ahead of the API write."""

    allocations: dict[str, ResourceClaim] = field(default_factory=dict)

    def assume(self, claim: ResourceClaim) -> None:
        self.allocations[claim.key()] = claim

    def restore(self, key: str) -> None:
        self.allocations.pop(key, None)

    def get(self, key: str) -> Optional[ResourceClaim]:
        return self.allocations.get(key)


class DynamicResources(PreFilterPlugin, FilterPlugin, ReservePlugin,
                       PreBindPlugin):
    NAME = "DynamicResources"
    STATE_KEY = "DynamicResources/claims"
    ASSUMED_KEY = "DynamicResources/assumed"

    def __init__(self, hub):
        import threading

        from kubernetes_tpu.hub import EventHandlers

        self.hub = hub
        self.assume = ClaimAssumeCache()
        # incremental allocated-device ledger + per-node device index,
        # maintained by claim/slice watch events — replaces the
        # O(all claims x all slices) rescan per pod that dominated at
        # reference DRA scale (thousands of slices). _ledger_lock guards
        # against the binder pool's PreBind claim writes dispatching
        # concurrently with the loop thread's reads.
        self._ledger_lock = threading.Lock()
        self._alloc_of: dict[str, frozenset] = {}   # claim key -> triples
        self._in_use: dict[tuple, int] = {}         # triple -> refcount
        self._claim_rv: dict[str, int] = {}         # claim key -> newest rv
        self._node_devices: dict[str, list] = {}    # node -> [(drv,pool,Device)]
        self._slice_entries: dict[str, tuple] = {}  # slice uid -> (node, n)
        # (epoch, expression, id(device)) -> bool; devices are held
        # strongly by _node_devices while their verdicts matter, and the
        # epoch bumps on slice removal so an allocator thread racing the
        # removal can only insert entries no future lookup reaches
        # (id(dev) may be reused after GC)
        self._sel_cache: dict[tuple, bool] = {}
        self._sel_epoch = 0
        # CEL selector failures surfaced instead of silently parking
        # pods: per-source counts (the dra_cel_errors_total mirror) and
        # a (source, expression) dedup set so a broken expression records
        # ONE hub Event per object, not one per (pod, node, device)
        self._cel_errors: dict[str, int] = {}
        self._cel_seen: set[tuple] = set()
        # batched device allocator (ops/dra.py): the view mirrors the
        # slice inventory into dense tensors + precompiled selector
        # masks; pods it routes skip the host filter (applies() False)
        # because the fused launch carries their DRA verdict. The set is
        # refreshed by every build_device_batch call and cleared when
        # the scheduler degrades a batch to the host path.
        self.device_view = DeviceAllocatorView(self)
        self._device_routed: frozenset[str] = frozenset()
        hub.watch_resource_claims(EventHandlers(
            on_add=self._claim_event,
            on_update=lambda old, new: self._claim_event(new),
            on_delete=self._claim_removed))
        hub.watch_resource_slices(EventHandlers(
            on_add=self._slice_added, on_delete=self._slice_removed))

    def applies(self, pod: Pod) -> bool:
        """Host-filter relevance probe: claims present AND the pod was
        not routed through the device allocator for the current batch
        (the fused launch already carries routed pods' verdicts)."""
        return bool(pod.spec.resource_claims) \
            and pod.metadata.uid not in self._device_routed

    def set_device_routed(self, uids) -> None:
        """Scheduler seam: which pods the CURRENT batch evaluates on
        device. Cleared (empty) before any host-path pass — the host
        fallback ladder must re-enable the host DRA filter."""
        self._device_routed = frozenset(uids)

    def build_device_batch(self, pods: list[Pod], row_of, n_cap: int,
                           b_cap: int):
        """Pack this batch's DraBatch tensors (or None) + build stats;
        refreshes the device-routing set as a side effect."""
        return self.device_view.build(pods, row_of, n_cap, b_cap)

    # --- the incremental ledger (claim/slice watch maintenance) ---

    def _apply_triples(self, key: str, triples: frozenset) -> None:
        """Ledger-lock-held: replace one claim's contribution."""
        old = self._alloc_of.get(key, frozenset())
        if old == triples:
            return
        for t in old - triples:
            n = self._in_use.get(t, 0) - 1
            if n <= 0:
                self._in_use.pop(t, None)
            else:
                self._in_use[t] = n
        for t in triples - old:
            self._in_use[t] = self._in_use.get(t, 0) + 1
        if triples:
            self._alloc_of[key] = triples
        else:
            self._alloc_of.pop(key, None)

    def _claim_event(self, claim: ResourceClaim) -> None:
        alloc = claim.status.allocation
        triples = frozenset(
            (d.driver, d.pool, d.device)
            for d in (alloc.devices if alloc is not None else ())
            if not d.admin_access)      # admin access never blocks others
        key = claim.key()
        rv = claim.metadata.resource_version
        with self._ledger_lock:
            # hub dispatch happens outside the hub lock, so a binder
            # thread's update and the loop thread's delete can arrive out
            # of commit order: the rv guard keeps a late update from
            # resurrecting a deleted claim's devices forever (hub rvs are
            # globally monotonic, so recreations are covered too)
            if rv <= self._claim_rv.get(key, -1):
                return
            self._claim_rv[key] = rv
            self._apply_triples(key, triples)

    def _claim_removed(self, claim: ResourceClaim) -> None:
        key = claim.key()
        with self._ledger_lock:
            self._claim_rv[key] = max(claim.metadata.resource_version,
                                      self._claim_rv.get(key, -1))
            if len(self._claim_rv) > 100_000:   # bound tombstone growth:
                # keep the newest half (stale events are short races)
                keep = sorted(self._claim_rv.items(),
                              key=lambda kv: kv[1])[50_000:]
                self._claim_rv = dict(keep)
            self._apply_triples(key, frozenset())

    def _slice_added(self, sl) -> None:
        with self._ledger_lock:
            entries = self._node_devices.setdefault(sl.node_name, [])
            for dev in sl.devices:
                entries.append((sl.driver, sl.pool, dev))
            self._slice_entries[sl.metadata.uid] = (sl.node_name,
                                                    sl.driver, sl.pool,
                                                    {d.name
                                                     for d in sl.devices})
        # outside the ledger lock (view lock -> ledger lock ordering)
        self.device_view.invalidate_node(sl.node_name)

    def _slice_removed(self, sl) -> None:
        with self._ledger_lock:
            meta = self._slice_entries.pop(sl.metadata.uid, None)
            if meta is None:
                return
            node, driver, pool, names = meta
            self._node_devices[node] = [
                (drv, pl, dev)
                for drv, pl, dev in self._node_devices.get(node, [])
                if not (drv == driver and pl == pool and dev.name in names)]
            # dropped Device objects may be GC'd and their ids reused —
            # bump the epoch (old-epoch keys become unreachable even if a
            # racing allocator inserts after this clear) and drop the bulk
            self._sel_epoch += 1
            self._sel_cache.clear()
        self.device_view.invalidate_node(node)

    def _in_use_view(self, exclude_keys: set[str]) -> set[tuple]:
        """Triples taken by any claim — ledger truth overlaid with assumed
        allocations — except the excluded claims'."""
        with self._ledger_lock:
            used = {t for t, n in self._in_use.items() if n > 0}
            base_alloc = dict(self._alloc_of)
        for key, claim in list(self.assume.allocations.items()):
            # overlay replaces the stored claim's contribution entirely
            used -= base_alloc.get(key, frozenset())
            alloc = claim.status.allocation
            if alloc is not None and key not in exclude_keys:
                used |= {(d.driver, d.pool, d.device)
                         for d in alloc.devices if not d.admin_access}
        for key in exclude_keys:
            if key not in self.assume.allocations:
                used -= base_alloc.get(key, frozenset())
        return used

    def _devices_on(self, node_name: str) -> list:
        with self._ledger_lock:
            return list(self._node_devices.get(node_name, ()))

    # --- views through the assume overlay ---

    def _claim(self, ns: str, name: str) -> Optional[ResourceClaim]:
        c = self.hub.get_resource_claim(ns, name)
        if c is None:
            return None
        assumed = self.assume.get(c.key())
        return assumed if assumed is not None else c

    def _pod_claims(self, pod: Pod):
        for ref in pod.spec.resource_claims:
            yield ref, self._claim(pod.metadata.namespace,
                                   claim_name_for(pod, ref))

    # --- the structured allocator (the reference's staging allocator) ---

    def _selector_accepts(self, expression: str, entry,
                          source: tuple[str, str]) -> bool:
        """One CEL selector against one device, MEMOIZED: a device's
        attributes are immutable for its lifetime in the slice index, so
        (expression, device) verdicts never change — without the cache
        the steady-state template workload re-evaluates the same
        expression over the same 800 devices for every (pod, node).
        A CelError (broken expression) counts as no-match but is
        SURFACED: a hub Event on the source object + the per-source
        error count the scheduler mirrors into dra_cel_errors_total."""
        driver, _pool, dev = entry
        key = (self._sel_epoch, expression, id(dev))
        hit = self._sel_cache.get(key)
        if hit is not None:
            return hit
        try:
            ok = evaluate(expression,
                          CelDevice(driver, dev.attributes, dev.capacity))
        except CelError as e:
            ok = False
            self._record_cel_error(source, expression, e)
        if len(self._sel_cache) > 500_000:
            self._sel_cache.clear()
        self._sel_cache[key] = ok
        return ok

    def _record_cel_error(self, source: tuple[str, str],
                          expression: str, err: Exception) -> None:
        kind, key = source
        src = f"{kind}/{key}"
        with self._ledger_lock:
            if (src, expression) in self._cel_seen:
                return
            self._cel_seen.add((src, expression))
            self._cel_errors[src] = self._cel_errors.get(src, 0) + 1
        try:
            self.hub.record_event(
                kind, key, "CELSelectorError",
                f"selector {expression!r} failed: {err}")
        except Exception:  # noqa: BLE001 — best-effort: an unreachable
            # hub must not turn a diagnostic into a scheduling failure
            pass

    def cel_error_stats(self) -> dict[str, int]:
        """{source object: distinct broken expressions} — mirrored into
        dra_cel_errors_total by the scheduler's maintenance tick."""
        with self._ledger_lock:
            return dict(self._cel_errors)

    def _cel_error_hint(self, claim: ResourceClaim) -> str:
        """Names the broken selector source touching ``claim``, if any —
        appended to the Filter's unschedulable message so a parked pod's
        condition points at the actual offender."""
        with self._ledger_lock:
            if not self._cel_errors:
                return ""
            if f"ResourceClaim/{claim.key()}" in self._cel_errors:
                return f"broken CEL selector on claim {claim.key()}"
            for req in claim.spec.device_requests:
                for alt in (req.first_available or [req]):
                    src = f"DeviceClass/{alt.device_class_name}"
                    if alt.device_class_name and src in self._cel_errors:
                        return ("broken CEL selector on deviceclass "
                                f"{alt.device_class_name}")
        return ""

    def _device_matches(self, entry, class_name: str, device_class,
                        selectors, claim_key: str) -> bool:
        """entry = (driver, pool, Device). DeviceClass CEL selectors (or
        the legacy direct device_class_name match when no class object
        exists) AND the request's own CEL selectors must all accept.
        ``device_class`` is the pre-resolved DeviceClass (resolved once
        per alternative, not per device — the allocator runs this for
        every device on every candidate node)."""
        _driver, _pool, dev = entry
        if class_name:
            if device_class is not None:
                for sel in device_class.selectors:
                    if not self._selector_accepts(
                            sel.cel_expression, entry,
                            ("DeviceClass", class_name)):
                        return False
            elif dev.device_class_name != class_name:
                return False
        for sel in selectors:
            if not self._selector_accepts(sel.cel_expression, entry,
                                          ("ResourceClaim", claim_key)):
                return False
        return True

    @staticmethod
    def _attr_of(entry, attribute: str):
        """matchAttribute resolution: qualified 'domain/name' keys match
        directly; plain keys resolve against the device's own driver
        domain (mirroring utils.cel._DomainMap)."""
        driver, _pool, dev = entry
        if attribute in dev.attributes:
            return dev.attributes[attribute]
        if "/" in attribute:
            dom, name = attribute.split("/", 1)
            if dom == driver:
                return dev.attributes.get(name)
        return None

    def allocate_claim(self, claim: ResourceClaim, node_name: str,
                       in_use: set[tuple]
                       ) -> Optional[list[DeviceAllocationResult]]:
        """Pick concrete devices on ``node_name`` satisfying every request
        of ``claim`` (ExactCount/All modes, firstAvailable alternatives,
        adminAccess, matchAttribute constraints), or None. Used by both
        Filter (feasibility = non-None) and Reserve (the actual pick), so
        the two can never diverge."""
        devices = self._devices_on(node_name)
        constraints = claim.spec.constraints
        picked: list[DeviceAllocationResult] = []
        taken: set[tuple] = set()
        locked: dict[int, object] = {}      # constraint idx -> value

        def applicable(parent_name):
            # a constraint names PARENT requests; it binds every
            # subrequest of a firstAvailable parent (empty = all requests)
            return [ci for ci, c in enumerate(constraints)
                    if not c.requests or parent_name in c.requests]

        def constraint_ok(cis, entry):
            for ci in cis:
                v = self._attr_of(entry, constraints[ci].match_attribute)
                if v is None or (ci in locked and locked[ci] != v):
                    return False
            return True

        def lock(cis, entry):
            for ci in cis:
                locked[ci] = self._attr_of(entry,
                                           constraints[ci].match_attribute)

        def fill(matched, cis, want, req_name, admin) -> bool:
            got = 0
            for entry, triple in matched:
                if got == want:
                    break
                if triple in taken or not constraint_ok(cis, entry):
                    continue
                lock(cis, entry)
                taken.add(triple)
                picked.append(DeviceAllocationResult(
                    request=req_name, driver=entry[0], pool=entry[1],
                    device=entry[2].name, admin_access=admin))
                got += 1
            return got == want

        def try_alternative(parent_name, req_name, class_name, selectors,
                            count, mode, admin) -> bool:
            device_class = (self.hub.get_device_class(class_name)
                            if class_name else None)
            matched = []
            for entry in devices:
                triple = (entry[0], entry[1], entry[2].name)
                if triple in taken:
                    continue
                if not admin and triple in in_use:
                    continue
                if not self._device_matches(entry, class_name,
                                            device_class, selectors,
                                            claim.key()):
                    continue
                matched.append((entry, triple))
            want = len(matched) if mode == ALLOCATION_MODE_ALL else count
            if len(matched) < want or want == 0:
                return False
            cis = applicable(parent_name)
            unlocked = [ci for ci in cis if ci not in locked]
            if not unlocked:
                return fill(matched, cis, want, req_name, admin)
            # unlocked matchAttribute constraints: a greedy first pick can
            # lock the wrong value ([A,B,B] with count=2 must pick B) —
            # try each candidate device as the constraint ANCHOR
            save = (list(picked), set(taken), dict(locked))
            for anchor, _t in matched:
                if not constraint_ok(cis, anchor):
                    continue
                lock(cis, anchor)
                if fill(matched, cis, want, req_name, admin):
                    return True
                picked[:] = save[0]
                taken.clear()
                taken.update(save[1])
                locked.clear()
                locked.update(save[2])
            return False

        for req in claim.spec.device_requests:
            alternatives = ([(f"{req.name}/{sub.name}", sub)
                             for sub in req.first_available]
                            if req.first_available else [(req.name, req)])
            satisfied = False
            for alt_name, alt in alternatives:
                save = (list(picked), set(taken), dict(locked))
                if try_alternative(req.name, alt_name,
                                   alt.device_class_name,
                                   alt.selectors, alt.count,
                                   alt.allocation_mode,
                                   getattr(alt, "admin_access", False)):
                    satisfied = True
                    break
                picked[:] = save[0]
                taken.clear()
                taken.update(save[1])
                locked.clear()
                locked.update(save[2])
            if not satisfied:
                return None
        return picked

    # --- extension points ---

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        if not pod.spec.resource_claims:
            return Status.skip()
        claims = []
        for ref, claim in self._pod_claims(pod):
            if claim is None:
                return Status.unschedulable(
                    f'resourceclaim "{claim_name_for(pod, ref)}" '
                    "not found", plugin=self.NAME, resolvable=False)
            claims.append(claim)
        state.write(self.STATE_KEY, claims)
        # exclude only the pod's UNALLOCATED claims: an allocated claim's
        # devices are taken no matter who reads the view (excluding it
        # would let a sibling claim double-book them)
        exclude = {c.key() for c in claims
                   if c.status.allocation is None}
        state.write(self.STATE_KEY + "/in_use",
                    self._in_use_view(exclude))
        return Status()

    def filter(self, state, pod: Pod, node_info) -> Status:
        claims = state.read(self.STATE_KEY) or []
        in_use = state.read(self.STATE_KEY + "/in_use") or set()
        node_name = node_info.node.metadata.name
        # claims share node devices: feasibility must thread one claim's
        # picks into the next's in-use view
        local_use = in_use
        for claim in claims:
            alloc = claim.status.allocation
            if alloc is not None:
                if alloc.node_name and alloc.node_name != node_name:
                    return Status.unschedulable(
                        "claim already allocated on another node",
                        plugin=self.NAME)
                continue
            picked = self.allocate_claim(claim, node_name, local_use)
            if picked is None:
                hint = self._cel_error_hint(claim)
                return Status.unschedulable(
                    "cannot allocate all claims"
                    + (f" ({hint})" if hint else ""), plugin=self.NAME)
            if len(claims) > 1:
                if local_use is in_use:
                    local_use = set(in_use)
                local_use |= {(d.driver, d.pool, d.device)
                              for d in picked if not d.admin_access}
        return Status()

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        assumed_keys = []
        claims = []
        for ref, c in self._pod_claims(pod):
            if c is None:
                return Status.unschedulable(
                    f'resourceclaim "{claim_name_for(pod, ref)}" '
                    "disappeared", plugin=self.NAME)
            claims.append(c)
        exclude = {c.key() for c in claims
                   if c.status.allocation is None}
        in_use = self._in_use_view(exclude)
        for claim in claims:
            if claim.status.allocation is not None:
                # already allocated: record this pod as a consumer
                if pod.metadata.uid not in claim.status.reserved_for:
                    new = claim.clone()
                    new.status.reserved_for.append(pod.metadata.uid)
                    self.assume.assume(new)
                    assumed_keys.append(new.key())
                continue
            picked = self.allocate_claim(claim, node_name, in_use)
            if picked is None:
                for k in assumed_keys:
                    self.assume.restore(k)
                return Status.unschedulable(
                    "devices vanished before reserve", plugin=self.NAME)
            in_use = in_use | {(d.driver, d.pool, d.device)
                               for d in picked if not d.admin_access}
            new = claim.clone()
            new.status.allocation = AllocationResult(
                node_name=node_name, devices=picked)
            if pod.metadata.uid not in new.status.reserved_for:
                new.status.reserved_for.append(pod.metadata.uid)
            self.assume.assume(new)
            assumed_keys.append(new.key())
        state.write(self.ASSUMED_KEY, assumed_keys)
        return Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        for key in state.read(self.ASSUMED_KEY) or []:
            self.assume.restore(key)

    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        for key in state.read(self.ASSUMED_KEY) or []:
            assumed = self.assume.get(key)
            if assumed is None:
                continue
            ns, name = key.split("/", 1)
            stored = self.hub.get_resource_claim(ns, name)
            if stored is None:
                return Status.error(f"resourceclaim {key} disappeared",
                                    plugin=self.NAME)
            try:
                new = stored.clone()
                if assumed.status.allocation is not None:
                    new.status.allocation = assumed.status.allocation
                merged = list(new.status.reserved_for)
                for uid in assumed.status.reserved_for:
                    if uid not in merged:
                        merged.append(uid)
                new.status.reserved_for = merged
                self.hub.update_resource_claim(new)
            except Unavailable:
                raise    # transport outage: degraded mode parks the pod
            except Exception as e:  # noqa: BLE001 — surfaced as Status
                return Status.error(str(e), plugin=self.NAME)
            self.assume.restore(key)
        return Status()
