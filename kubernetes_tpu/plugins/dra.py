"""DynamicResources: the DRA scheduler plugin, TPU-native host edition.

From-scratch equivalent of the reference's accelerator-scheduling path
(plugins/dynamicresources/dynamicresources.go:105-888 + the structured
allocator under staging/src/k8s.io/dynamic-resource-allocation): pods
reference ResourceClaims; DRA drivers publish per-node device inventories
as ResourceSlices; the plugin

- PreFilter: resolve the pod's claims — direct names or per-pod claims
  generated from ResourceClaimTemplates (pod.status.resourceClaimStatuses
  written by the ResourceClaimController below) — missing claim =>
  unresolvable; no claims => Skip; build the free-device view per node
  from the incremental allocated-device ledger + the assume overlay,
- Filter: a node fits iff every unallocated claim can be ALLOCATED from
  that node's remaining devices (structured parameters: per-request CEL
  selectors + DeviceClass selectors, ExactCount/All modes, firstAvailable
  alternatives, adminAccess, matchAttribute constraints), and every
  already-allocated claim is pinned to its allocation's node,
- Reserve: run the same allocator on the chosen node and ASSUME the
  allocation (assume overlay — the scheduler-side AssumeCache the
  reference keeps for claims), Unreserve reverts,
- PreBind: write the allocation + reservedFor to the API (hub).

Restart safety is API-truth-based like everything else in this build: a
restarted scheduler rebuilds its view from claim statuses, so allocations
survive replay and allocated devices never double-book.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.objects import (
    ALLOCATION_MODE_ALL,
    AllocationResult,
    DeviceAllocationResult,
    ObjectMeta,
    Pod,
    ResourceClaim,
)
from kubernetes_tpu.hub import Unavailable
from kubernetes_tpu.framework.interface import (
    FilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    Status,
)
from kubernetes_tpu.utils.cel import CelDevice, CelError, evaluate


def claim_name_for(pod: Pod, ref) -> str:
    """Resolve a pod.spec.resourceClaims entry to a claim NAME: direct
    reference, or the controller-generated name for a template reference
    (pod.status.resourceClaimStatuses, falling back to the deterministic
    '<pod>-<ref>' convention the controller uses)."""
    if ref.resource_claim_name:
        return ref.resource_claim_name
    if ref.resource_claim_template_name:
        return (pod.status.resource_claim_statuses.get(ref.name)
                or f"{pod.metadata.name}-{ref.name}")
    return ref.name


def dra_serial_keys(hub, pod: Pod) -> set[str]:
    """Host-serial conflict domains: two pods referencing the SAME claim
    must not share a batch (the first one's assume — allocation or
    reservedFor append — changes what the second must see).

    Pods with DISTINCT claims deliberately DO share batches even when
    their claims compete for one device class: reserve() re-walks the
    free-device view through the assume overlay sequentially at commit
    time and fails cleanly ("devices vanished") into the requeue path, so
    a same-batch capacity race costs one retry, never a double-booking.
    Serializing per device class instead was measured at ~50x throughput
    loss (one claim pod per launch) on DRA steady-state."""
    keys: set[str] = set()
    for ref in pod.spec.resource_claims:
        claim = hub.get_resource_claim(pod.metadata.namespace,
                                       claim_name_for(pod, ref))
        if claim is None:
            continue
        keys.add(f"draclaim:{claim.key()}")
    return keys


def release_pod_claims(hub, pod: Pod) -> None:
    """The slice of the reference's resourceclaim controller the scheduler
    build needs: a deleted pod leaves its claims' reservedFor. The
    ALLOCATION persists — a standalone claim owns its devices until the
    claim itself is deleted (that is how users hand a device from pod to
    pod); freeing capacity means deleting the claim, whose event requeues
    waiting DRA pods."""
    for ref in pod.spec.resource_claims:
        claim = hub.get_resource_claim(pod.metadata.namespace,
                                       claim_name_for(pod, ref))
        if claim is None \
                or pod.metadata.uid not in claim.status.reserved_for:
            continue
        new = claim.clone()
        new.status.reserved_for.remove(pod.metadata.uid)
        hub.update_resource_claim(new)


class ResourceClaimController:
    """The resourceclaim controller slice this build needs (the reference
    runs the full version in kube-controller-manager,
    pkg/controller/resourceclaim): watches pods, stamps a per-pod
    ResourceClaim out of each referenced ResourceClaimTemplate under the
    deterministic name '<pod>-<ref>', records the generated names in
    pod.status.resourceClaimStatuses, and deletes the owned claims when
    the pod goes away (template-generated claims die with their pod;
    directly-referenced claims persist)."""

    def __init__(self, hub):
        from kubernetes_tpu.hub import EventHandlers

        self.hub = hub
        hub.watch_pods(EventHandlers(on_add=self._on_pod_add,
                                     on_delete=self._on_pod_delete))
        # a pod can reference a template created AFTER it (the reference
        # controller retries via its workqueue): re-stamp waiting pods
        # when their template appears
        hub.watch_resource_claim_templates(EventHandlers(
            on_add=self._on_template_add))

    def _on_template_add(self, tmpl) -> None:
        for pod in self.hub.list_pods():
            if any(ref.resource_claim_template_name == tmpl.metadata.name
                   and pod.metadata.namespace == tmpl.metadata.namespace
                   for ref in pod.spec.resource_claims):
                self._on_pod_add(pod)

    def _on_pod_add(self, pod: Pod) -> None:
        import copy

        statuses: dict[str, str] = {}
        for ref in pod.spec.resource_claims:
            if not ref.resource_claim_template_name:
                continue
            name = f"{pod.metadata.name}-{ref.name}"
            tmpl = self.hub.get_resource_claim_template(
                pod.metadata.namespace, ref.resource_claim_template_name)
            if tmpl is None:
                continue    # the template watch re-stamps on its arrival
            if self.hub.get_resource_claim(pod.metadata.namespace,
                                           name) is None:
                self.hub.create_resource_claim(ResourceClaim(
                    metadata=ObjectMeta(name=name,
                                        namespace=pod.metadata.namespace),
                    spec=copy.deepcopy(tmpl.spec)))
            statuses[ref.name] = name
        if statuses and pod.status.resource_claim_statuses != statuses:
            self.hub.set_pod_claim_statuses(pod.metadata.uid, statuses)

    def _on_pod_delete(self, pod: Pod) -> None:
        for ref in pod.spec.resource_claims:
            if not ref.resource_claim_template_name:
                continue
            name = (pod.status.resource_claim_statuses.get(ref.name)
                    or f"{pod.metadata.name}-{ref.name}")
            claim = self.hub.get_resource_claim(pod.metadata.namespace,
                                                name)
            if claim is not None:
                self.hub.delete_resource_claim(claim.metadata.uid)


@dataclass
class ClaimAssumeCache:
    """Assumed claim allocations ahead of the API write."""

    allocations: dict[str, ResourceClaim] = field(default_factory=dict)

    def assume(self, claim: ResourceClaim) -> None:
        self.allocations[claim.key()] = claim

    def restore(self, key: str) -> None:
        self.allocations.pop(key, None)

    def get(self, key: str) -> Optional[ResourceClaim]:
        return self.allocations.get(key)


class DynamicResources(PreFilterPlugin, FilterPlugin, ReservePlugin,
                       PreBindPlugin):
    NAME = "DynamicResources"
    STATE_KEY = "DynamicResources/claims"
    ASSUMED_KEY = "DynamicResources/assumed"

    def __init__(self, hub):
        import threading

        from kubernetes_tpu.hub import EventHandlers

        self.hub = hub
        self.assume = ClaimAssumeCache()
        # incremental allocated-device ledger + per-node device index,
        # maintained by claim/slice watch events — replaces the
        # O(all claims x all slices) rescan per pod that dominated at
        # reference DRA scale (thousands of slices). _ledger_lock guards
        # against the binder pool's PreBind claim writes dispatching
        # concurrently with the loop thread's reads.
        self._ledger_lock = threading.Lock()
        self._alloc_of: dict[str, frozenset] = {}   # claim key -> triples
        self._in_use: dict[tuple, int] = {}         # triple -> refcount
        self._claim_rv: dict[str, int] = {}         # claim key -> newest rv
        self._node_devices: dict[str, list] = {}    # node -> [(drv,pool,Device)]
        self._slice_entries: dict[str, tuple] = {}  # slice uid -> (node, n)
        # (epoch, expression, id(device)) -> bool; devices are held
        # strongly by _node_devices while their verdicts matter, and the
        # epoch bumps on slice removal so an allocator thread racing the
        # removal can only insert entries no future lookup reaches
        # (id(dev) may be reused after GC)
        self._sel_cache: dict[tuple, bool] = {}
        self._sel_epoch = 0
        # CEL selector failures surfaced instead of silently parking
        # pods: per-source counts (the dra_cel_errors_total mirror) and
        # a (source, expression) dedup set so a broken expression records
        # ONE hub Event per object, not one per (pod, node, device)
        self._cel_errors: dict[str, int] = {}
        self._cel_seen: set[tuple] = set()
        hub.watch_resource_claims(EventHandlers(
            on_add=self._claim_event,
            on_update=lambda old, new: self._claim_event(new),
            on_delete=self._claim_removed))
        hub.watch_resource_slices(EventHandlers(
            on_add=self._slice_added, on_delete=self._slice_removed))

    @staticmethod
    def applies(pod: Pod) -> bool:
        return bool(pod.spec.resource_claims)

    # --- the incremental ledger (claim/slice watch maintenance) ---

    def _apply_triples(self, key: str, triples: frozenset) -> None:
        """Ledger-lock-held: replace one claim's contribution."""
        old = self._alloc_of.get(key, frozenset())
        if old == triples:
            return
        for t in old - triples:
            n = self._in_use.get(t, 0) - 1
            if n <= 0:
                self._in_use.pop(t, None)
            else:
                self._in_use[t] = n
        for t in triples - old:
            self._in_use[t] = self._in_use.get(t, 0) + 1
        if triples:
            self._alloc_of[key] = triples
        else:
            self._alloc_of.pop(key, None)

    def _claim_event(self, claim: ResourceClaim) -> None:
        alloc = claim.status.allocation
        triples = frozenset(
            (d.driver, d.pool, d.device)
            for d in (alloc.devices if alloc is not None else ())
            if not d.admin_access)      # admin access never blocks others
        key = claim.key()
        rv = claim.metadata.resource_version
        with self._ledger_lock:
            # hub dispatch happens outside the hub lock, so a binder
            # thread's update and the loop thread's delete can arrive out
            # of commit order: the rv guard keeps a late update from
            # resurrecting a deleted claim's devices forever (hub rvs are
            # globally monotonic, so recreations are covered too)
            if rv <= self._claim_rv.get(key, -1):
                return
            self._claim_rv[key] = rv
            self._apply_triples(key, triples)

    def _claim_removed(self, claim: ResourceClaim) -> None:
        key = claim.key()
        with self._ledger_lock:
            self._claim_rv[key] = max(claim.metadata.resource_version,
                                      self._claim_rv.get(key, -1))
            if len(self._claim_rv) > 100_000:   # bound tombstone growth:
                # keep the newest half (stale events are short races)
                keep = sorted(self._claim_rv.items(),
                              key=lambda kv: kv[1])[50_000:]
                self._claim_rv = dict(keep)
            self._apply_triples(key, frozenset())

    def _slice_added(self, sl) -> None:
        with self._ledger_lock:
            entries = self._node_devices.setdefault(sl.node_name, [])
            for dev in sl.devices:
                entries.append((sl.driver, sl.pool, dev))
            self._slice_entries[sl.metadata.uid] = (sl.node_name,
                                                    sl.driver, sl.pool,
                                                    {d.name
                                                     for d in sl.devices})
    def _slice_removed(self, sl) -> None:
        with self._ledger_lock:
            meta = self._slice_entries.pop(sl.metadata.uid, None)
            if meta is None:
                return
            node, driver, pool, names = meta
            self._node_devices[node] = [
                (drv, pl, dev)
                for drv, pl, dev in self._node_devices.get(node, [])
                if not (drv == driver and pl == pool and dev.name in names)]
            # dropped Device objects may be GC'd and their ids reused —
            # bump the epoch (old-epoch keys become unreachable even if a
            # racing allocator inserts after this clear) and drop the bulk
            self._sel_epoch += 1
            self._sel_cache.clear()

    def _in_use_view(self, exclude_keys: set[str]) -> set[tuple]:
        """Triples taken by any claim — ledger truth overlaid with assumed
        allocations — except the excluded claims'."""
        with self._ledger_lock:
            used = {t for t, n in self._in_use.items() if n > 0}
            base_alloc = dict(self._alloc_of)
        for key, claim in list(self.assume.allocations.items()):
            # overlay replaces the stored claim's contribution entirely
            used -= base_alloc.get(key, frozenset())
            alloc = claim.status.allocation
            if alloc is not None and key not in exclude_keys:
                used |= {(d.driver, d.pool, d.device)
                         for d in alloc.devices if not d.admin_access}
        for key in exclude_keys:
            if key not in self.assume.allocations:
                used -= base_alloc.get(key, frozenset())
        return used

    def _devices_on(self, node_name: str) -> list:
        with self._ledger_lock:
            return list(self._node_devices.get(node_name, ()))

    # --- views through the assume overlay ---

    def _claim(self, ns: str, name: str) -> Optional[ResourceClaim]:
        c = self.hub.get_resource_claim(ns, name)
        if c is None:
            return None
        assumed = self.assume.get(c.key())
        return assumed if assumed is not None else c

    def _pod_claims(self, pod: Pod):
        for ref in pod.spec.resource_claims:
            yield ref, self._claim(pod.metadata.namespace,
                                   claim_name_for(pod, ref))

    # --- the structured allocator (the reference's staging allocator) ---

    def _selector_accepts(self, expression: str, entry,
                          source: tuple[str, str]) -> bool:
        """One CEL selector against one device, MEMOIZED: a device's
        attributes are immutable for its lifetime in the slice index, so
        (expression, device) verdicts never change — without the cache
        the steady-state template workload re-evaluates the same
        expression over the same 800 devices for every (pod, node).
        A CelError (broken expression) counts as no-match but is
        SURFACED: a hub Event on the source object + the per-source
        error count the scheduler mirrors into dra_cel_errors_total."""
        driver, _pool, dev = entry
        key = (self._sel_epoch, expression, id(dev))
        hit = self._sel_cache.get(key)
        if hit is not None:
            return hit
        try:
            ok = evaluate(expression,
                          CelDevice(driver, dev.attributes, dev.capacity))
        except CelError as e:
            ok = False
            self._record_cel_error(source, expression, e)
        if len(self._sel_cache) > 500_000:
            self._sel_cache.clear()
        self._sel_cache[key] = ok
        return ok

    def _record_cel_error(self, source: tuple[str, str],
                          expression: str, err: Exception) -> None:
        kind, key = source
        src = f"{kind}/{key}"
        with self._ledger_lock:
            if (src, expression) in self._cel_seen:
                return
            self._cel_seen.add((src, expression))
            self._cel_errors[src] = self._cel_errors.get(src, 0) + 1
        try:
            self.hub.record_event(
                kind, key, "CELSelectorError",
                f"selector {expression!r} failed: {err}")
        except Exception:  # noqa: BLE001 — best-effort: an unreachable
            # hub must not turn a diagnostic into a scheduling failure
            pass

    def cel_error_stats(self) -> dict[str, int]:
        """{source object: distinct broken expressions} — mirrored into
        dra_cel_errors_total by the scheduler's maintenance tick."""
        with self._ledger_lock:
            return dict(self._cel_errors)

    def _cel_error_hint(self, claim: ResourceClaim) -> str:
        """Names the broken selector source touching ``claim``, if any —
        appended to the Filter's unschedulable message so a parked pod's
        condition points at the actual offender."""
        with self._ledger_lock:
            if not self._cel_errors:
                return ""
            if f"ResourceClaim/{claim.key()}" in self._cel_errors:
                return f"broken CEL selector on claim {claim.key()}"
            for req in claim.spec.device_requests:
                for alt in (req.first_available or [req]):
                    src = f"DeviceClass/{alt.device_class_name}"
                    if alt.device_class_name and src in self._cel_errors:
                        return ("broken CEL selector on deviceclass "
                                f"{alt.device_class_name}")
        return ""

    def _device_matches(self, entry, class_name: str, device_class,
                        selectors, claim_key: str) -> bool:
        """entry = (driver, pool, Device). DeviceClass CEL selectors (or
        the legacy direct device_class_name match when no class object
        exists) AND the request's own CEL selectors must all accept.
        ``device_class`` is the pre-resolved DeviceClass (resolved once
        per alternative, not per device — the allocator runs this for
        every device on every candidate node)."""
        _driver, _pool, dev = entry
        if class_name:
            if device_class is not None:
                for sel in device_class.selectors:
                    if not self._selector_accepts(
                            sel.cel_expression, entry,
                            ("DeviceClass", class_name)):
                        return False
            elif dev.device_class_name != class_name:
                return False
        for sel in selectors:
            if not self._selector_accepts(sel.cel_expression, entry,
                                          ("ResourceClaim", claim_key)):
                return False
        return True

    @staticmethod
    def _attr_of(entry, attribute: str):
        """matchAttribute resolution: qualified 'domain/name' keys match
        directly; plain keys resolve against the device's own driver
        domain (mirroring utils.cel._DomainMap)."""
        driver, _pool, dev = entry
        if attribute in dev.attributes:
            return dev.attributes[attribute]
        if "/" in attribute:
            dom, name = attribute.split("/", 1)
            if dom == driver:
                return dev.attributes.get(name)
        return None

    def allocate_claim(self, claim: ResourceClaim, node_name: str,
                       in_use: set[tuple]
                       ) -> Optional[list[DeviceAllocationResult]]:
        """Pick concrete devices on ``node_name`` satisfying every request
        of ``claim`` (ExactCount/All modes, firstAvailable alternatives,
        adminAccess, matchAttribute constraints), or None. Used by both
        Filter (feasibility = non-None) and Reserve (the actual pick), so
        the two can never diverge."""
        devices = self._devices_on(node_name)
        constraints = claim.spec.constraints
        picked: list[DeviceAllocationResult] = []
        taken: set[tuple] = set()
        locked: dict[int, object] = {}      # constraint idx -> value

        def applicable(parent_name):
            # a constraint names PARENT requests; it binds every
            # subrequest of a firstAvailable parent (empty = all requests)
            return [ci for ci, c in enumerate(constraints)
                    if not c.requests or parent_name in c.requests]

        def constraint_ok(cis, entry):
            for ci in cis:
                v = self._attr_of(entry, constraints[ci].match_attribute)
                if v is None or (ci in locked and locked[ci] != v):
                    return False
            return True

        def lock(cis, entry):
            for ci in cis:
                locked[ci] = self._attr_of(entry,
                                           constraints[ci].match_attribute)

        def fill(matched, cis, want, req_name, admin) -> bool:
            got = 0
            for entry, triple in matched:
                if got == want:
                    break
                if triple in taken or not constraint_ok(cis, entry):
                    continue
                lock(cis, entry)
                taken.add(triple)
                picked.append(DeviceAllocationResult(
                    request=req_name, driver=entry[0], pool=entry[1],
                    device=entry[2].name, admin_access=admin))
                got += 1
            return got == want

        def try_alternative(parent_name, req_name, class_name, selectors,
                            count, mode, admin) -> bool:
            device_class = (self.hub.get_device_class(class_name)
                            if class_name else None)
            matched = []
            for entry in devices:
                triple = (entry[0], entry[1], entry[2].name)
                if triple in taken:
                    continue
                if not admin and triple in in_use:
                    continue
                if not self._device_matches(entry, class_name,
                                            device_class, selectors,
                                            claim.key()):
                    continue
                matched.append((entry, triple))
            want = len(matched) if mode == ALLOCATION_MODE_ALL else count
            if len(matched) < want or want == 0:
                return False
            cis = applicable(parent_name)
            unlocked = [ci for ci in cis if ci not in locked]
            if not unlocked:
                return fill(matched, cis, want, req_name, admin)
            # unlocked matchAttribute constraints: a greedy first pick can
            # lock the wrong value ([A,B,B] with count=2 must pick B) —
            # try each candidate device as the constraint ANCHOR
            save = (list(picked), set(taken), dict(locked))
            for anchor, _t in matched:
                if not constraint_ok(cis, anchor):
                    continue
                lock(cis, anchor)
                if fill(matched, cis, want, req_name, admin):
                    return True
                picked[:] = save[0]
                taken.clear()
                taken.update(save[1])
                locked.clear()
                locked.update(save[2])
            return False

        for req in claim.spec.device_requests:
            alternatives = ([(f"{req.name}/{sub.name}", sub)
                             for sub in req.first_available]
                            if req.first_available else [(req.name, req)])
            satisfied = False
            for alt_name, alt in alternatives:
                save = (list(picked), set(taken), dict(locked))
                if try_alternative(req.name, alt_name,
                                   alt.device_class_name,
                                   alt.selectors, alt.count,
                                   alt.allocation_mode,
                                   getattr(alt, "admin_access", False)):
                    satisfied = True
                    break
                picked[:] = save[0]
                taken.clear()
                taken.update(save[1])
                locked.clear()
                locked.update(save[2])
            if not satisfied:
                return None
        return picked

    # --- extension points ---

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        if not pod.spec.resource_claims:
            return Status.skip()
        claims = []
        for ref, claim in self._pod_claims(pod):
            if claim is None:
                return Status.unschedulable(
                    f'resourceclaim "{claim_name_for(pod, ref)}" '
                    "not found", plugin=self.NAME, resolvable=False)
            claims.append(claim)
        state.write(self.STATE_KEY, claims)
        # exclude only the pod's UNALLOCATED claims: an allocated claim's
        # devices are taken no matter who reads the view (excluding it
        # would let a sibling claim double-book them)
        exclude = {c.key() for c in claims
                   if c.status.allocation is None}
        state.write(self.STATE_KEY + "/in_use",
                    self._in_use_view(exclude))
        return Status()

    def filter(self, state, pod: Pod, node_info) -> Status:
        claims = state.read(self.STATE_KEY) or []
        in_use = state.read(self.STATE_KEY + "/in_use") or set()
        node_name = node_info.node.metadata.name
        # claims share node devices: feasibility must thread one claim's
        # picks into the next's in-use view
        local_use = in_use
        for claim in claims:
            alloc = claim.status.allocation
            if alloc is not None:
                if alloc.node_name and alloc.node_name != node_name:
                    return Status.unschedulable(
                        "claim already allocated on another node",
                        plugin=self.NAME)
                continue
            picked = self.allocate_claim(claim, node_name, local_use)
            if picked is None:
                hint = self._cel_error_hint(claim)
                return Status.unschedulable(
                    "cannot allocate all claims"
                    + (f" ({hint})" if hint else ""), plugin=self.NAME)
            if len(claims) > 1:
                if local_use is in_use:
                    local_use = set(in_use)
                local_use |= {(d.driver, d.pool, d.device)
                              for d in picked if not d.admin_access}
        return Status()

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        assumed_keys = []
        claims = []
        for ref, c in self._pod_claims(pod):
            if c is None:
                return Status.unschedulable(
                    f'resourceclaim "{claim_name_for(pod, ref)}" '
                    "disappeared", plugin=self.NAME)
            claims.append(c)
        exclude = {c.key() for c in claims
                   if c.status.allocation is None}
        in_use = self._in_use_view(exclude)
        for claim in claims:
            if claim.status.allocation is not None:
                # already allocated: record this pod as a consumer
                if pod.metadata.uid not in claim.status.reserved_for:
                    new = claim.clone()
                    new.status.reserved_for.append(pod.metadata.uid)
                    self.assume.assume(new)
                    assumed_keys.append(new.key())
                continue
            picked = self.allocate_claim(claim, node_name, in_use)
            if picked is None:
                for k in assumed_keys:
                    self.assume.restore(k)
                return Status.unschedulable(
                    "devices vanished before reserve", plugin=self.NAME)
            in_use = in_use | {(d.driver, d.pool, d.device)
                               for d in picked if not d.admin_access}
            new = claim.clone()
            new.status.allocation = AllocationResult(
                node_name=node_name, devices=picked)
            if pod.metadata.uid not in new.status.reserved_for:
                new.status.reserved_for.append(pod.metadata.uid)
            self.assume.assume(new)
            assumed_keys.append(new.key())
        state.write(self.ASSUMED_KEY, assumed_keys)
        return Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        for key in state.read(self.ASSUMED_KEY) or []:
            self.assume.restore(key)

    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        for key in state.read(self.ASSUMED_KEY) or []:
            assumed = self.assume.get(key)
            if assumed is None:
                continue
            ns, name = key.split("/", 1)
            stored = self.hub.get_resource_claim(ns, name)
            if stored is None:
                return Status.error(f"resourceclaim {key} disappeared",
                                    plugin=self.NAME)
            try:
                new = stored.clone()
                if assumed.status.allocation is not None:
                    new.status.allocation = assumed.status.allocation
                merged = list(new.status.reserved_for)
                for uid in assumed.status.reserved_for:
                    if uid not in merged:
                        merged.append(uid)
                new.status.reserved_for = merged
                self.hub.update_resource_claim(new)
            except Unavailable:
                raise    # transport outage: degraded mode parks the pod
            except Exception as e:  # noqa: BLE001 — surfaced as Status
                return Status.error(str(e), plugin=self.NAME)
            self.assume.restore(key)
        return Status()
