"""QueueingHintFns for the big in-tree plugins.

Each fn answers "can THIS event make THIS rejected pod schedulable?"
(QueueingHintFn, framework/types.go:248) so non-helpful events leave pods
parked instead of thundering the activeQ. Semantics mirror the reference's
per-plugin isSchedulableAfter* fns:

- NodeResourcesFit: fit.go:265 isSchedulableAfterNodeChange /
  isSchedulableAfterPodEvent — a node only helps if the pod's request fits
  its allocatable; only a SCHEDULED pod's deletion helps (it frees real
  capacity, including its pod slot).
- NodeAffinity: node_affinity.go:95 — the (new) node must match the pod's
  required affinity/selector.
- TaintToleration: taint_toleration.go:205 — every NoSchedule taint on the
  new node must be tolerated.
- InterPodAffinity: plugin.go:92 — an appearing/relabeled pod only helps a
  required-affinity rejection if it matches a term; a deleted pod only
  helps an anti-affinity rejection if it matched one.
- PodTopologySpread: plugin.go:160 — pod events only help if the pod
  matches some constraint's selector in the pending pod's namespace; node
  events only help if they touch a constraint's topology key.
"""

from __future__ import annotations

from kubernetes_tpu.api.labels import (
    find_untolerated_taint,
    label_selector_matches,
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.api.resources import Resource, pod_request
from kubernetes_tpu.framework.interface import QueueingHint

QUEUE = QueueingHint.QUEUE
SKIP = QueueingHint.SKIP


def _as_node(obj) -> Node | None:
    return obj if isinstance(obj, Node) else None


def _as_pod(obj) -> Pod | None:
    return obj if isinstance(obj, Pod) else None


def fit_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """NodeResourcesFit (fit.go:265): node events QUEUE only when the pod's
    request fits the new node's allocatable; a SCHEDULED pod's deletion
    always queues (it frees its node's pod slot even with zero requests,
    isSchedulableAfterPodEvent), an unscheduled pod's never does."""
    node = _as_node(new_obj)
    if node is not None:
        req = pod_request(pod)
        alloc = Resource.from_map(node.status.allocatable)
        fits = (req.milli_cpu <= alloc.milli_cpu
                and req.memory <= alloc.memory
                and req.ephemeral_storage <= alloc.ephemeral_storage
                and all(alloc.scalar.get(k, 0) >= v
                        for k, v in req.scalar.items()))
        return QUEUE if fits else SKIP
    old_pod = _as_pod(old_obj)
    if old_pod is not None and new_obj is None:     # deletion
        scheduled = (old_pod.spec.node_name
                     or old_pod.status.nominated_node_name)
        return QUEUE if scheduled else SKIP
    return QUEUE    # scale-down / unknown shape: be conservative


def node_affinity_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    node = _as_node(new_obj)
    if node is None:
        return QUEUE
    return (QUEUE if pod_matches_node_selector_and_affinity(pod, node)
            else SKIP)


def taint_toleration_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    node = _as_node(new_obj)
    if node is None:
        return QUEUE
    untolerated = find_untolerated_taint(node.spec.taints,
                                         pod.spec.tolerations)
    return SKIP if untolerated is not None else QUEUE


def _pod_matches_terms(terms, other: Pod, pending_ns: str) -> bool:
    for term in terms:
        namespaces = term.namespaces or [pending_ns]
        if other.metadata.namespace not in namespaces \
                and term.namespace_selector is None:
            continue
        if label_selector_matches(term.label_selector,
                                  other.metadata.labels):
            return True
    return False


def _anti_terms_could_block(p: Pod, pending: Pod) -> bool:
    """Does p carry a required anti-affinity term whose selector could
    actually select ``pending``? (The departed blocker must have been able
    to block THIS pod, else its exit is noise.)"""
    a = p.spec.affinity
    if a is None or a.pod_anti_affinity is None:
        return False
    return _pod_matches_terms(a.pod_anti_affinity.required, pending,
                              p.metadata.namespace)


def inter_pod_affinity_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """plugin.go:92 isSchedulableAfterPodChange: appearing/relabeled pods
    help required affinity; disappearing (or relabeled-away) pods help
    required anti-affinity — including EXISTING pods' anti-affinity: the
    filter also rejects pods blocked by a running pod's own required
    anti terms (satisfyExistingPodsAntiAffinity), so the departure of any
    anti-affinity-carrying pod can unstick a pod with no terms at all."""
    new_pod = _as_pod(new_obj)
    old_pod = _as_pod(old_obj)
    if new_pod is None and old_pod is None:
        return QUEUE        # node label event: could open a topology domain
    aff = pod.spec.affinity
    if new_pod is not None:
        if aff is not None and aff.pod_affinity is not None \
                and _pod_matches_terms(aff.pod_affinity.required, new_pod,
                                       pod.metadata.namespace):
            return QUEUE
        # label update that moves a pod OUT of the pending pod's required
        # anti selector (or drops the pod's own anti terms)
        if old_pod is not None:
            if aff is not None and aff.pod_anti_affinity is not None \
                    and _pod_matches_terms(aff.pod_anti_affinity.required,
                                           old_pod, pod.metadata.namespace) \
                    and not _pod_matches_terms(
                        aff.pod_anti_affinity.required, new_pod,
                        pod.metadata.namespace):
                return QUEUE
            if _anti_terms_could_block(old_pod, pod) \
                    and not _anti_terms_could_block(new_pod, pod):
                return QUEUE
        return SKIP
    # deletion
    if aff is not None and aff.pod_anti_affinity is not None \
            and _pod_matches_terms(aff.pod_anti_affinity.required, old_pod,
                                   pod.metadata.namespace):
        return QUEUE
    if _anti_terms_could_block(old_pod, pod):
        return QUEUE        # its own anti terms could have blocked us
    return SKIP


def topology_spread_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """plugin.go:160 isSchedulableAfterPodChange: only pods matching some
    constraint's selector in the pending pod's namespace move the skew."""
    other = _as_pod(new_obj) or _as_pod(old_obj)
    if other is None:
        keys = {c.topology_key
                for c in pod.spec.topology_spread_constraints}
        # a key appearing on the NEW node or leaving the OLD one both move
        # the domain math (isSchedulableAfterNodeChange checks both sides)
        for node in (_as_node(new_obj), _as_node(old_obj)):
            if node is not None \
                    and any(k in node.metadata.labels for k in keys):
                return QUEUE
        if _as_node(new_obj) is None and _as_node(old_obj) is None:
            return QUEUE
        return SKIP
    if other.metadata.namespace != pod.metadata.namespace:
        return SKIP
    for c in pod.spec.topology_spread_constraints:
        if label_selector_matches(c.label_selector, other.metadata.labels):
            return QUEUE
        old_pod = _as_pod(old_obj)
        if old_pod is not None and label_selector_matches(
                c.label_selector, old_pod.metadata.labels):
            return QUEUE    # label update out of the matching set
    return SKIP
