"""QueueingHintFns for the big in-tree plugins.

Each fn answers "can THIS event make THIS rejected pod schedulable?"
(QueueingHintFn, framework/types.go:248) so non-helpful events leave pods
parked instead of thundering the activeQ. Semantics mirror the reference's
per-plugin isSchedulableAfter* fns:

- NodeResourcesFit: fit.go:265 isSchedulableAfterNodeChange /
  isSchedulableAfterPodEvent — a node only helps if the pod's request fits
  its allocatable; only a SCHEDULED pod's deletion helps (it frees real
  capacity, including its pod slot).
- NodeAffinity: node_affinity.go:95 — the (new) node must match the pod's
  required affinity/selector.
- TaintToleration: taint_toleration.go:205 — every NoSchedule taint on the
  new node must be tolerated.
- InterPodAffinity: plugin.go:92 — an appearing/relabeled pod only helps a
  required-affinity rejection if it matches a term; a deleted pod only
  helps an anti-affinity rejection if it matched one.
- PodTopologySpread: plugin.go:160 — pod events only help if the pod
  matches some constraint's selector in the pending pod's namespace; node
  events only help if they touch a constraint's topology key.
"""

from __future__ import annotations

from kubernetes_tpu.api.labels import (
    find_untolerated_taint,
    label_selector_matches,
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.api.resources import Resource, pod_request
from kubernetes_tpu.framework.interface import QueueingHint

QUEUE = QueueingHint.QUEUE
SKIP = QueueingHint.SKIP


def _as_node(obj) -> Node | None:
    return obj if isinstance(obj, Node) else None


def _as_pod(obj) -> Pod | None:
    return obj if isinstance(obj, Pod) else None


def fit_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """NodeResourcesFit (fit.go:265): node events QUEUE only when the pod's
    request fits the new node's allocatable; a SCHEDULED pod's deletion
    always queues (it frees its node's pod slot even with zero requests,
    isSchedulableAfterPodEvent), an unscheduled pod's never does."""
    node = _as_node(new_obj)
    if node is not None:
        req = pod_request(pod)
        alloc = Resource.from_map(node.status.allocatable)
        fits = (req.milli_cpu <= alloc.milli_cpu
                and req.memory <= alloc.memory
                and req.ephemeral_storage <= alloc.ephemeral_storage
                and all(alloc.scalar.get(k, 0) >= v
                        for k, v in req.scalar.items()))
        return QUEUE if fits else SKIP
    old_pod = _as_pod(old_obj)
    if old_pod is not None and new_obj is None:     # deletion
        scheduled = (old_pod.spec.node_name
                     or old_pod.status.nominated_node_name)
        return QUEUE if scheduled else SKIP
    return QUEUE    # scale-down / unknown shape: be conservative


def node_affinity_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    node = _as_node(new_obj)
    if node is None:
        return QUEUE
    return (QUEUE if pod_matches_node_selector_and_affinity(pod, node)
            else SKIP)


def taint_toleration_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    node = _as_node(new_obj)
    if node is None:
        return QUEUE
    untolerated = find_untolerated_taint(node.spec.taints,
                                         pod.spec.tolerations)
    return SKIP if untolerated is not None else QUEUE


def _pod_matches_terms(terms, other: Pod, pending_ns: str) -> bool:
    for term in terms:
        namespaces = term.namespaces or [pending_ns]
        if other.metadata.namespace not in namespaces \
                and term.namespace_selector is None:
            continue
        if label_selector_matches(term.label_selector,
                                  other.metadata.labels):
            return True
    return False


def _anti_terms_could_block(p: Pod, pending: Pod) -> bool:
    """Does p carry a required anti-affinity term whose selector could
    actually select ``pending``? (The departed blocker must have been able
    to block THIS pod, else its exit is noise.)"""
    a = p.spec.affinity
    if a is None or a.pod_anti_affinity is None:
        return False
    return _pod_matches_terms(a.pod_anti_affinity.required, pending,
                              p.metadata.namespace)


def inter_pod_affinity_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """plugin.go:92 isSchedulableAfterPodChange: appearing/relabeled pods
    help required affinity; disappearing (or relabeled-away) pods help
    required anti-affinity — including EXISTING pods' anti-affinity: the
    filter also rejects pods blocked by a running pod's own required
    anti terms (satisfyExistingPodsAntiAffinity), so the departure of any
    anti-affinity-carrying pod can unstick a pod with no terms at all."""
    new_pod = _as_pod(new_obj)
    old_pod = _as_pod(old_obj)
    if new_pod is None and old_pod is None:
        return QUEUE        # node label event: could open a topology domain
    aff = pod.spec.affinity
    if new_pod is not None:
        if aff is not None and aff.pod_affinity is not None \
                and _pod_matches_terms(aff.pod_affinity.required, new_pod,
                                       pod.metadata.namespace):
            return QUEUE
        # label update that moves a pod OUT of the pending pod's required
        # anti selector (or drops the pod's own anti terms)
        if old_pod is not None:
            if aff is not None and aff.pod_anti_affinity is not None \
                    and _pod_matches_terms(aff.pod_anti_affinity.required,
                                           old_pod, pod.metadata.namespace) \
                    and not _pod_matches_terms(
                        aff.pod_anti_affinity.required, new_pod,
                        pod.metadata.namespace):
                return QUEUE
            if _anti_terms_could_block(old_pod, pod) \
                    and not _anti_terms_could_block(new_pod, pod):
                return QUEUE
        return SKIP
    # deletion
    if aff is not None and aff.pod_anti_affinity is not None \
            and _pod_matches_terms(aff.pod_anti_affinity.required, old_pod,
                                   pod.metadata.namespace):
        return QUEUE
    if _anti_terms_could_block(old_pod, pod):
        return QUEUE        # its own anti terms could have blocked us
    return SKIP


def topology_spread_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """plugin.go:160 isSchedulableAfterPodChange: only pods matching some
    constraint's selector in the pending pod's namespace move the skew."""
    other = _as_pod(new_obj) or _as_pod(old_obj)
    if other is None:
        keys = {c.topology_key
                for c in pod.spec.topology_spread_constraints}
        # a key appearing on the NEW node or leaving the OLD one both move
        # the domain math (isSchedulableAfterNodeChange checks both sides)
        for node in (_as_node(new_obj), _as_node(old_obj)):
            if node is not None \
                    and any(k in node.metadata.labels for k in keys):
                return QUEUE
        if _as_node(new_obj) is None and _as_node(old_obj) is None:
            return QUEUE
        return SKIP
    if other.metadata.namespace != pod.metadata.namespace:
        return SKIP
    for c in pod.spec.topology_spread_constraints:
        if label_selector_matches(c.label_selector, other.metadata.labels):
            return QUEUE
        old_pod = _as_pod(old_obj)
        if old_pod is not None and label_selector_matches(
                c.label_selector, old_pod.metadata.labels):
            return QUEUE    # label update out of the matching set
    return SKIP


# ------------- volume family / DRA / gates / ports hints -------------
# The remaining per-plugin isSchedulableAfter* fns: without them every
# PV/PVC/claim/slice event thundered the whole unschedulable pool.


def _pod_host_ports(p: Pod) -> set[tuple[str, int]]:
    out = set()
    for c in p.spec.containers:
        for prt in c.ports:
            if prt.host_port:
                out.add((prt.protocol or "TCP", prt.host_port))
    return out


def node_ports_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """nodeports.go isSchedulableAfterPodDeleted: a deleted pod helps
    only if it held a host port the pending pod wants."""
    old_pod = _as_pod(old_obj)
    if old_pod is not None and new_obj is None:
        if not old_pod.spec.node_name:
            return SKIP
        want = _pod_host_ports(pod)
        held = _pod_host_ports(old_pod)
        return QUEUE if want & held else SKIP
    return QUEUE    # node events: allocatable/new node could host the port


def _pod_claim_names(pod: Pod) -> set[str]:
    from kubernetes_tpu.plugins.dra import claim_name_for

    return {claim_name_for(pod, ref) for ref in pod.spec.resource_claims}


def dra_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """dynamicresources.go isSchedulableAfterClaimChange /
    ...ResourceSliceChange: the pod's OWN claim appearing/changing helps
    (template-generated claims arrive late; deallocation frees its
    devices); ANY claim's deletion frees devices; a new/removed slice
    changes the device inventory."""
    obj = new_obj if new_obj is not None else old_obj
    kind = type(obj).__name__ if obj is not None else ""
    if kind == "ResourceClaim":
        if new_obj is None:
            return QUEUE        # deletion frees its devices for anyone
        if obj.metadata.namespace == pod.metadata.namespace \
                and obj.metadata.name in _pod_claim_names(pod):
            return QUEUE        # the pod's own claim appeared / changed
        old_claim = old_obj
        if old_claim is not None \
                and old_claim.status.allocation is not None \
                and new_obj.status.allocation is None:
            return QUEUE        # a claim deallocated: devices freed
        return SKIP
    if kind == "ResourceSlice":
        return QUEUE            # inventory changed either way
    return QUEUE                # node/pod events: conservative


def _pod_pvc_names(pod: Pod) -> set[str]:
    out = set()
    for v in pod.spec.volumes:
        pvc_src = getattr(v, "persistent_volume_claim", None)
        if pvc_src is not None:
            out.add(pvc_src.claim_name)
    return out


def volume_binding_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """volume_binding.go isSchedulableAfter{PVC,PV,StorageClass,
    CSIStorageCapacity}Change: only objects that can serve one of the
    pod's claims help."""
    obj = new_obj if new_obj is not None else old_obj
    kind = type(obj).__name__ if obj is not None else ""
    if kind == "PersistentVolumeClaim":
        return (QUEUE if obj.metadata.namespace == pod.metadata.namespace
                and obj.metadata.name in _pod_pvc_names(pod) else SKIP)
    # PV / StorageClass / CSIStorageCapacity / node events: the pod's
    # claim set cannot be resolved to classes without the hub, so any
    # such event may help (the reference checks class names; this stays
    # one notch more conservative, still far from wildcard)
    return QUEUE


def _restricted_volume_keys(p: Pod) -> set[str]:
    """Type-prefixed restricted-volume identities (reuses volume.py's
    _restricted_key so gce/rbd/etc. namespaces can never collide)."""
    from kubernetes_tpu.plugins.volume import _restricted_key

    out = set()
    for v in p.spec.volumes:
        k = _restricted_key(v) if hasattr(v, "gce_pd_name") else None
        if k is not None:
            out.add(k)
    return out


def volume_restrictions_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """volume_restrictions.go isSchedulableAfterPodDeleted: the departed
    pod must have shared a restricted volume or a ReadWriteOncePod claim
    namespace-wise; PVC adds must belong to the pod."""
    old_pod = _as_pod(old_obj)
    if old_pod is not None and new_obj is None:
        if not old_pod.spec.node_name:
            return SKIP
        if old_pod.metadata.namespace != pod.metadata.namespace:
            # restricted non-PVC volumes conflict cross-namespace
            return (QUEUE if _restricted_volume_keys(pod)
                    & _restricted_volume_keys(old_pod) else SKIP)
        return (QUEUE if _pod_pvc_names(pod) & _pod_pvc_names(old_pod)
                or _restricted_volume_keys(pod)
                & _restricted_volume_keys(old_pod) else SKIP)
    if type(new_obj).__name__ == "PersistentVolumeClaim":
        return (QUEUE
                if new_obj.metadata.namespace == pod.metadata.namespace
                and new_obj.metadata.name in _pod_pvc_names(pod) else SKIP)
    return QUEUE


def node_volume_limits_hint(pod: Pod, old_obj, new_obj) -> QueueingHint:
    """csi.go isSchedulableAfterPodDeleted: a departed pod frees attach
    slots only if it mounted PVC-backed volumes."""
    old_pod = _as_pod(old_obj)
    if old_pod is not None and new_obj is None:
        if not old_pod.spec.node_name:
            return SKIP
        return QUEUE if _pod_pvc_names(old_pod) else SKIP
    return QUEUE    # CSINode / PVC / PV events: limits or claims changed
