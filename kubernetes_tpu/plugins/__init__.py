from kubernetes_tpu.plugins.registry import (  # noqa: F401
    DEVICE_FILTER_PLUGINS,
    DEVICE_SCORE_PLUGINS,
    PluginDescriptor,
    in_tree_registry,
)
