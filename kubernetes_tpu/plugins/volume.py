"""The volume plugin family: host Filter/Reserve/PreBind plugins.

From-scratch equivalents of the reference's volume plugins, run on host
around the device launch (the mixed host/device framework, SURVEY §7.0 —
volume state is small, pointer-chasing, and API-coupled: exactly the work
that does NOT belong on the TPU):

- VolumeZone       (plugins/volumezone/volume_zone.go): a bound PVC's PV
  carries zone/region labels; the node must match them.
- VolumeRestrictions (plugins/volumerestrictions/volume_restrictions.go):
  GCE-PD / AWS-EBS / iSCSI / RBD read-write conflicts on a node, and the
  ReadWriteOncePod access-mode conflict (:77-199).
- NodeVolumeLimits (plugins/nodevolumelimits/csi.go): attachable CSI
  volume count per node vs the node's allocatable limit.
- VolumeBinding    (plugins/volumebinding/volume_binding.go +
  scheduler_binder.go): bound-PV node affinity at Filter; unbound
  WaitForFirstConsumer PVCs matched to available PVs (or provisionable
  classes) at Filter, assumed at Reserve via an AssumeCache
  (util/assumecache/assume_cache.go), written to the API at PreBind.

Host filters evaluate per (pod, node_info) and their verdicts are ANDed
into the device result as a host mask (Framework.run_host_filters →
Scheduler._dispatch → pipeline host_ok).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.labels import (
    label_selector_matches,
    node_selector_matches,
)
from kubernetes_tpu.api.objects import (
    LABEL_REGION,
    LABEL_ZONE,
    READ_WRITE_ONCE_POD,
    VOLUME_BINDING_WAIT,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    Volume,
)
from kubernetes_tpu.hub import Unavailable
from kubernetes_tpu.utils.quantity import parse_bytes, parse_int
from kubernetes_tpu.framework.interface import (
    FilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)

# legacy + GA zone/region label keys (volume_zone.go:55-60)
ZONE_LABELS = (
    LABEL_ZONE,
    LABEL_REGION,
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


def _pod_pvcs(hub, pod: Pod):
    """Yield (volume, pvc_or_None) for each PVC-backed volume."""
    for v in pod.spec.volumes:
        if isinstance(v, Volume) and v.persistent_volume_claim is not None:
            pvc = hub.get_pvc(pod.metadata.namespace,
                              v.persistent_volume_claim.claim_name)
            yield v, pvc


def _restricted_key(v: Volume) -> Optional[str]:
    """Conflict-domain identity of a directly-attached restricted volume."""
    if v.gce_pd_name:
        return f"gce:{v.gce_pd_name}"
    if v.aws_ebs_volume_id:
        return f"ebs:{v.aws_ebs_volume_id}"
    if v.iscsi_iqn:
        return f"iscsi:{v.iscsi_iqn}"
    if v.rbd_image:
        return f"rbd:{v.rbd_image}"
    return None


def host_serial_keys(hub, pod: Pod) -> set[str]:
    """Conflict-domain keys that force as-if-serial batching on the HOST
    side: two pods sharing a key must not be filtered within one batch,
    because the first one's placement changes the second one's verdict
    (Scheduler defers the second to the next batch)."""
    keys: set[str] = set()
    for v in pod.spec.volumes:
        if not isinstance(v, Volume):
            continue
        k = _restricted_key(v)
        if k is not None:
            keys.add(k)
        if v.persistent_volume_claim is not None:
            pvc = hub.get_pvc(pod.metadata.namespace,
                              v.persistent_volume_claim.claim_name)
            if pvc is not None:
                if READ_WRITE_ONCE_POD in pvc.spec.access_modes:
                    keys.add(f"rwop:{pvc.key()}")
                if not pvc.spec.volume_name:
                    # unbound PVCs of one storage class compete for the
                    # same PV pool — serialize per class, not per claim
                    keys.add(f"bindsc:{pvc.spec.storage_class_name}")
                else:
                    pv = hub.get_pv(pvc.spec.volume_name)
                    if pv is not None and pv.spec.csi_driver:
                        # attach-limit accounting is per (node, driver):
                        # a second same-driver pod in the batch would see
                        # stale counts (NodeVolumeLimits)
                        keys.add(f"csi:{pv.spec.csi_driver}")
    return keys


class VolumeZone(PreFilterPlugin, FilterPlugin):
    """volume_zone.go:77 (Filter), :191 (PreFilter Skip without PVCs)."""

    NAME = "VolumeZone"

    @staticmethod
    def applies(pod: Pod) -> bool:
        return bool(pod.spec.volumes)

    def __init__(self, hub):
        self.hub = hub

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        for _v, _pvc in _pod_pvcs(self.hub, pod):
            return Status()
        return Status.skip()

    def filter(self, state, pod: Pod, node_info) -> Status:
        node = node_info.node
        for v, pvc in _pod_pvcs(self.hub, pod):
            if pvc is None:
                return Status.unschedulable(
                    f'persistentvolumeclaim "'
                    f'{v.persistent_volume_claim.claim_name}" not found',
                    plugin=self.NAME, resolvable=False)
            if not pvc.spec.volume_name:
                continue            # unbound: VolumeBinding's business
            pv = self.hub.get_pv(pvc.spec.volume_name)
            if pv is None:
                continue
            for key in ZONE_LABELS:
                want = pv.metadata.labels.get(key)
                if want is None:
                    continue
                # PV zone labels may hold a __ separated set (volume_zone.go
                # uses LabelZonesToSet)
                allowed = set(want.split("__"))
                got = node.metadata.labels.get(key)
                if got not in allowed:
                    return Status.unschedulable(
                        "node(s) had no available volume zone",
                        plugin=self.NAME)
        return Status()


class VolumeRestrictions(PreFilterPlugin, FilterPlugin):
    """volume_restrictions.go: disk write conflicts on the node (:77-120)
    + ReadWriteOncePod conflicts (:126-199, cluster-wide at PreFilter)."""

    NAME = "VolumeRestrictions"

    @staticmethod
    def applies(pod: Pod) -> bool:
        return bool(pod.spec.volumes)

    def __init__(self, hub):
        self.hub = hub

    def _relevant(self, pod: Pod) -> bool:
        for v in pod.spec.volumes:
            if not isinstance(v, Volume):
                continue
            if _restricted_key(v) is not None:
                return True
            if v.persistent_volume_claim is not None:
                pvc = self.hub.get_pvc(
                    pod.metadata.namespace,
                    v.persistent_volume_claim.claim_name)
                if pvc is not None \
                        and READ_WRITE_ONCE_POD in pvc.spec.access_modes:
                    return True
        return False

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        if not self._relevant(pod):
            return Status.skip()
        # ReadWriteOncePod: at most one pod cluster-wide may use the claim
        for v, pvc in _pod_pvcs(self.hub, pod):
            if pvc is None or READ_WRITE_ONCE_POD not in pvc.spec.access_modes:
                continue
            for other in self.hub.list_pods():
                if other.metadata.uid == pod.metadata.uid \
                        or not other.spec.node_name \
                        or other.metadata.namespace != pod.metadata.namespace:
                    continue
                for ov in other.spec.volumes:
                    if (isinstance(ov, Volume)
                            and ov.persistent_volume_claim is not None
                            and ov.persistent_volume_claim.claim_name
                            == pvc.metadata.name):
                        return Status.unschedulable(
                            "pod uses a ReadWriteOncePod volume already in "
                            "use by another pod", plugin=self.NAME,
                            resolvable=False)
        return Status()

    def filter(self, state, pod: Pod, node_info) -> Status:
        mine = {}
        for v in pod.spec.volumes:
            if isinstance(v, Volume):
                k = _restricted_key(v)
                if k is not None:
                    mine[k] = v.read_only
        if not mine:
            return Status()
        for pi in node_info.pods:
            for ov in pi.pod.spec.volumes:
                if not isinstance(ov, Volume):
                    continue
                k = _restricted_key(ov)
                if k in mine:
                    # iSCSI/RBD allow read-only sharing; GCE/EBS never share
                    both_ro = mine[k] and ov.read_only
                    sharable = k.startswith(("iscsi:", "rbd:")) and both_ro
                    if not sharable:
                        return Status.unschedulable(
                            "node has a volume conflict", plugin=self.NAME)
        return Status()


class NodeVolumeLimits(PreFilterPlugin, FilterPlugin):
    """nodevolumelimits/csi.go: #attached CSI volumes per driver vs the
    node's allocatable `attachable-volumes-csi-<driver>` limit."""

    NAME = "NodeVolumeLimits"

    @staticmethod
    def applies(pod: Pod) -> bool:
        return bool(pod.spec.volumes)

    def __init__(self, hub):
        self.hub = hub

    def _csi_volumes(self, pod: Pod) -> set[tuple[str, str]]:
        """Unique (driver, pv_name) attachments the pod needs — attachments
        are per VOLUME, not per claim reference (csi.go dedupes by the
        volume's unique handle)."""
        out: set[tuple[str, str]] = set()
        for _v, pvc in _pod_pvcs(self.hub, pod):
            if pvc is None or not pvc.spec.volume_name:
                continue
            pv = self.hub.get_pv(pvc.spec.volume_name)
            if pv is not None and pv.spec.csi_driver:
                out.add((pv.spec.csi_driver, pv.metadata.name))
        return out

    STATE_KEY = "NodeVolumeLimits/volumes"

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        vols = self._csi_volumes(pod)
        if not vols:
            return Status.skip()
        state.write(self.STATE_KEY, vols)
        return Status()

    def filter(self, state, pod: Pod, node_info) -> Status:
        vols: set = state.read(self.STATE_KEY) or set()
        node = node_info.node
        drivers = {d for d, _ in vols}
        limits = {d: node.status.allocatable.get(
            f"attachable-volumes-csi-{d}") for d in drivers}
        if not any(v is not None for v in limits.values()):
            return Status()
        attached: set[tuple[str, str]] = set()
        for pi in node_info.pods:           # one pass over node pods
            attached |= self._csi_volumes(pi.pod)
        new_vols = vols - attached          # already-attached PVs are free
        for driver in drivers:
            limit_s = limits[driver]
            if limit_s is None:
                continue
            used = sum(1 for d, _ in attached if d == driver)
            new = sum(1 for d, _ in new_vols if d == driver)
            if used + new > parse_int(limit_s):
                return Status.unschedulable(
                    "node(s) exceed max volume count", plugin=self.NAME)
        return Status()


# --------------------------- VolumeBinding ---------------------------


@dataclass
class AssumeCache:
    """util/assumecache/assume_cache.go, reduced to what the binder needs:
    optimistic PV/PVC views layered over the hub until the API writes land
    or the assume is reverted."""

    pvs: dict[str, PersistentVolume] = field(default_factory=dict)
    pvcs: dict[str, PersistentVolumeClaim] = field(default_factory=dict)

    def assume_pv(self, pv: PersistentVolume) -> None:
        self.pvs[pv.metadata.name] = pv

    def assume_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self.pvcs[pvc.key()] = pvc

    def restore(self, pv_name: str = "", pvc_key: str = "") -> None:
        if pv_name:
            self.pvs.pop(pv_name, None)
        if pvc_key:
            self.pvcs.pop(pvc_key, None)


class VolumeBinding(PreFilterPlugin, FilterPlugin, ScorePlugin,
                    ReservePlugin, PreBindPlugin):
    """volume_binding.go Filter (:268) + Score (:464 storage-capacity
    fit) + Reserve (:318 AssumePodVolumes) + PreBind (:346
    BindPodVolumes, dynamic provisioning trigger) + Unreserve (:334
    revert)."""

    NAME = "VolumeBinding"
    STATE_KEY = "VolumeBinding/assumed"
    PLAN_KEY = "VolumeBinding/plan"

    @staticmethod
    def applies(pod: Pod) -> bool:
        return bool(pod.spec.volumes)

    def __init__(self, hub):
        self.hub = hub
        self.assume = AssumeCache()

    # --- hub views through the assume overlay ---

    def _pv(self, name: str) -> Optional[PersistentVolume]:
        return self.assume.pvs.get(name) or self.hub.get_pv(name)

    def _pvc(self, ns: str, name: str) -> Optional[PersistentVolumeClaim]:
        return (self.assume.pvcs.get(f"{ns}/{name}")
                or self.hub.get_pvc(ns, name))

    def _pod_claims(self, pod: Pod):
        for v in pod.spec.volumes:
            if isinstance(v, Volume) and v.persistent_volume_claim is not None:
                yield self._pvc(pod.metadata.namespace,
                                v.persistent_volume_claim.claim_name)

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        claims = list(self._pod_claims(pod))
        if not any(c is not None for c in claims):
            if any(v.persistent_volume_claim is not None
                   for v in pod.spec.volumes if isinstance(v, Volume)):
                return Status.unschedulable(
                    "persistentvolumeclaim not found", plugin=self.NAME,
                    resolvable=False)
            return Status.skip()
        for pvc in claims:
            if pvc is None:
                return Status.unschedulable(
                    "persistentvolumeclaim not found", plugin=self.NAME,
                    resolvable=False)
            if pvc.spec.volume_name:
                continue
            sc = self.hub.get_storage_class(pvc.spec.storage_class_name)
            mode = sc.volume_binding_mode if sc is not None else ""
            if mode != VOLUME_BINDING_WAIT:
                # unbound Immediate-mode claim: the PV controller must bind
                # it first (volume_binding.go:243)
                return Status.unschedulable(
                    "pod has unbound immediate PersistentVolumeClaims",
                    plugin=self.NAME, resolvable=False)
        # per-claim Filter work, computed once per pod (the reference's
        # PreFilter builds podVolumeClaims the same way): bound claims ->
        # their PV; unbound claims -> (class/access/size-matched candidate
        # PVs, the storage class when provisionable). Filter then checks
        # per-node affinity / provisioning topology+capacity against these.
        plan = []
        for pvc in claims:
            if pvc.spec.volume_name:
                pv = self._pv(pvc.spec.volume_name)
                if pv is None:
                    return Status.unschedulable(
                        f'persistentvolume "{pvc.spec.volume_name}" '
                        "not found", plugin=self.NAME, resolvable=False)
                plan.append(("bound", (pv, pvc)))
            else:
                cands = [pv for pv in
                         (self._pv(p.metadata.name) or p
                          for p in self.hub.list_pvs())
                         if self._pv_fits_claim(pv, pvc)]
                cands.sort(key=lambda pv: parse_bytes(
                    pv.spec.capacity.get("storage", "0")))
                sc2 = self.hub.get_storage_class(pvc.spec.storage_class_name)
                provision_class = (sc2 if sc2 is not None
                                   and sc2.provisioner else None)
                plan.append(("unbound", (cands, provision_class, pvc)))
        state.write(self.PLAN_KEY, plan)
        # per-class capacity index, built once per pod and probed per
        # node by Filter/Score
        cap_index = {}
        for kind, data in plan:
            if kind == "unbound" and data[1] is not None:
                cls = data[1].metadata.name
                if cls not in cap_index:
                    cap_index[cls] = self._class_capacities(cls)
        state.write(self.PLAN_KEY + "/caps", cap_index)
        return Status()

    # --- dynamic provisioning checks (binder.go checkVolumeProvisions) ---

    @staticmethod
    def _topology_allows(sc, node) -> bool:
        """StorageClass.allowedTopologies vs node labels
        (v1helper.MatchTopologySelectorTerms): any term whose every
        requirement matches; empty = everywhere."""
        if not sc.allowed_topologies:
            return True
        for term in sc.allowed_topologies:
            ok = True
            for req in term.match_label_expressions:
                if node.metadata.labels.get(req.key) not in req.values:
                    ok = False
                    break
            if ok:
                return True
        return False

    def _class_capacities(self, class_name: str) -> list:
        """All published CSIStorageCapacity entries for one class — ONE
        hub scan per pod (cached per class per call site), probed per
        node. The per-(node, claim) full-list rescan held the hub lock
        O(nodes x claims x capacities) times per pod."""
        out = []
        for cap in self.hub.list_csi_capacities():
            if cap.storage_class_name == class_name:
                out.append((cap.node_topology, parse_bytes(cap.capacity)))
        return out

    @staticmethod
    def _capacity_on_node(entries: list, node) -> Optional[int]:
        """Largest capacity among ``entries`` covering ``node``; None for
        an empty entry list — a class whose driver publishes nothing is
        exempt from capacity checking (binder.go hasEnoughCapacity's
        CSIDriver gate)."""
        if not entries:
            return None
        best = 0
        for sel, v in entries:
            if sel is not None and not label_selector_matches(
                    sel, node.metadata.labels):
                continue
            if v > best:
                best = v
        return best

    def _node_capacity_for(self, sc, node) -> Optional[int]:
        return self._capacity_on_node(
            self._class_capacities(sc.metadata.name), node)

    def _provision_ok(self, sc, pvc, node, entries=None) -> Optional[str]:
        """None when the node can host the provisioning; an unschedulable
        message otherwise (topology vs capacity attributed distinctly)."""
        if not self._topology_allows(sc, node):
            return "node(s) did not satisfy the storage class's " \
                   "allowedTopologies"
        cap = self._capacity_on_node(
            self._class_capacities(sc.metadata.name)
            if entries is None else entries, node)
        if cap is None:
            return None         # driver publishes no capacity: no check
        if cap >= parse_bytes(pvc.spec.requests.get("storage", "0")):
            return None
        return "node(s) did not have enough free storage"

    # --- matching (scheduler_binder.go findMatchingVolumes) ---

    def _pv_fits_claim(self, pv: PersistentVolume,
                       pvc: PersistentVolumeClaim) -> bool:
        if pv.spec.claim_ref is not None:
            return False
        if pv.spec.storage_class_name != pvc.spec.storage_class_name:
            return False
        if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
            return False
        want = parse_bytes(pvc.spec.requests.get("storage", "0"))
        got = parse_bytes(pv.spec.capacity.get("storage", "0"))
        return got >= want

    def _find_pv_for(self, pvc: PersistentVolumeClaim, node) -> Optional[
            PersistentVolume]:
        best = None
        best_cap = None
        for pv in self.hub.list_pvs():
            pv = self._pv(pv.metadata.name) or pv
            if not self._pv_fits_claim(pv, pvc):
                continue
            if not node_selector_matches(pv.spec.node_affinity, node):
                continue
            cap = parse_bytes(pv.spec.capacity.get("storage", "0"))
            if best is None or cap < best_cap:   # smallest fitting PV
                best, best_cap = pv, cap
        return best

    def filter(self, state, pod: Pod, node_info) -> Status:
        node = node_info.node
        cap_index = state.read(self.PLAN_KEY + "/caps") or {}
        for kind, data in state.read(self.PLAN_KEY) or []:
            if kind == "bound":
                pv, _pvc = data
                if not node_selector_matches(pv.spec.node_affinity, node):
                    return Status.unschedulable(
                        "node(s) had volume node affinity conflict",
                        plugin=self.NAME)
                continue
            cands, provision_class, pvc = data
            if any(node_selector_matches(pv.spec.node_affinity, node)
                   for pv in cands):
                continue            # a static PV covers it on this node
            if provision_class is not None:
                why = self._provision_ok(
                    provision_class, pvc, node,
                    entries=cap_index.get(provision_class.metadata.name))
                if why is None:
                    continue        # dynamic provisioning covers it
                return Status.unschedulable(why, plugin=self.NAME)
            return Status.unschedulable(
                "node(s) didn't find available persistent volumes to bind",
                plugin=self.NAME)
        return Status()

    # --- Score: storage-capacity fit (volume_binding.go:449-516) ---

    def score(self, state, pod: Pod, node_info) -> tuple[float, Status]:
        """Utilization-shaped capacity score per class: static bindings
        score by chosen-PV utilization (requested/capacity of the PVs this
        node would bind), dynamic provisions by requested/published
        CSIStorageCapacity — the reference's classResourceMap + shape
        scorer with the default 0->0, 100->10 shape."""
        plan = state.read(self.PLAN_KEY) or []
        if not plan:
            return 0.0, Status()
        node = node_info.node
        static: list[tuple] = []        # (want, chosen_pv, class)
        dynamic: list[tuple] = []       # (want, provision_class, class)
        for kind, data in plan:
            if kind == "bound":
                continue
            cands, provision_class, pvc = data
            want = parse_bytes(pvc.spec.requests.get("storage", "0"))
            chosen = None
            for pv in cands:
                if node_selector_matches(pv.spec.node_affinity, node):
                    if chosen is None or parse_bytes(
                            pv.spec.capacity.get("storage", "0")) < \
                            parse_bytes(chosen.spec.capacity.get(
                                "storage", "0")):
                        chosen = pv     # smallest fitting PV (the binder's
                                        # own choice order)
            cls = pvc.spec.storage_class_name
            if chosen is not None:
                static.append((want, chosen, cls))
            elif provision_class is not None:
                dynamic.append((want, provision_class, cls))
        by_class: dict[str, list[int]] = {}     # class -> [requested, cap]
        if static:
            # the reference scores static bindings whenever any exist,
            # dynamic provisions only otherwise (volume_binding.go:479) —
            # never mixing the two accountings within one pod
            for want, pv, cls in static:
                entry = by_class.setdefault(cls, [0, 0])
                entry[0] += want
                entry[1] += parse_bytes(
                    pv.spec.capacity.get("storage", "0"))
        else:
            cap_index = state.read(self.PLAN_KEY + "/caps") or {}
            for want, provision_class, cls in dynamic:
                entries = cap_index.get(cls)
                if entries is None:     # dict.get's default would EAGERLY
                    entries = self._class_capacities(cls)   # rescan the hub
                cap = self._capacity_on_node(entries, node)
                if cap:
                    entry = by_class.setdefault(cls, [0, 0])
                    entry[0] += want
                    # NOT +=: several claims of one class share the same
                    # published node capacity (volume_binding.go:505-509)
                    entry[1] = cap
        utils = [req / cap for req, cap in by_class.values() if cap > 0]
        if not utils:
            return 0.0, Status()
        # default shape {0: 0, 100: 10}: linear in utilization, averaged
        # over classes (higher utilization = tighter fit = better score)
        return 10.0 * (sum(utils) / len(utils)), Status()

    # --- Reserve: AssumePodVolumes ---

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        unbound = [pvc for pvc in self._pod_claims(pod)
                   if pvc is not None and not pvc.spec.volume_name]
        if not unbound:
            return Status()     # nothing to assume (the hot-path exit)
        node = self.hub.get_node(node_name)
        assumed = []
        for pvc in unbound:
            pv = self._find_pv_for(pvc, node) if node is not None else None
            if pv is None:
                sc = self.hub.get_storage_class(pvc.spec.storage_class_name)
                if sc is not None and sc.provisioner:
                    # dynamic provisioning: PreBind writes the
                    # selected-node annotation that triggers the external
                    # provisioner (binder.go BindPodVolumes)
                    assumed.append(("", pvc.key()))
                    continue
                for _pv_name, _pvc_key in assumed:
                    self.assume.restore(_pv_name, _pvc_key)
                return Status.unschedulable(
                    "no persistent volume to bind", plugin=self.NAME)
            new_pv = pv.clone()
            from kubernetes_tpu.api.objects import ClaimRef

            new_pv.spec.claim_ref = ClaimRef(
                namespace=pvc.metadata.namespace, name=pvc.metadata.name,
                uid=pvc.metadata.uid)
            new_pvc = pvc.clone()
            new_pvc.spec.volume_name = pv.metadata.name
            self.assume.assume_pv(new_pv)
            self.assume.assume_pvc(new_pvc)
            assumed.append((pv.metadata.name, new_pvc.key()))
        state.write(self.STATE_KEY, assumed)
        return Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        for pv_name, pvc_key in state.read(self.STATE_KEY) or []:
            self.assume.restore(pv_name, pvc_key)

    # --- PreBind: BindPodVolumes (API writes) ---

    # the annotation the external provisioner watches for
    # (volume.kubernetes.io/selected-node, scheduler_binder.go)
    SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"

    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        for pv_name, pvc_key in state.read(self.STATE_KEY) or []:
            if not pv_name:
                # dynamic provision: annotate the claim with the chosen
                # node; the (fake or real) PV controller provisions + binds
                ns, name = pvc_key.split("/", 1)
                stored_c = self.hub.get_pvc(ns, name)
                if stored_c is None:
                    return Status.error(
                        f"persistentvolumeclaim {pvc_key} disappeared",
                        plugin=self.NAME)
                try:
                    new_c = stored_c.clone()
                    new_c.metadata.annotations[
                        self.SELECTED_NODE_ANNOTATION] = node_name
                    self.hub.update_pvc(new_c)
                except Unavailable:
                    raise    # transport outage: degraded mode parks
                except Exception as e:  # noqa: BLE001
                    return Status.error(str(e), plugin=self.NAME)
                continue
            pv = self.assume.pvs.get(pv_name)
            pvc = self.assume.pvcs.get(pvc_key)
            try:
                if pv is not None:
                    stored = self.hub.get_pv(pv_name)
                    if stored is not None:
                        new = stored.clone()
                        new.spec.claim_ref = pv.spec.claim_ref
                        new.status.phase = "Bound"
                        self.hub.update_pv(new)
                if pvc is not None:
                    ns, name = pvc_key.split("/", 1)
                    stored_c = self.hub.get_pvc(ns, name)
                    if stored_c is not None:
                        new_c = stored_c.clone()
                        new_c.spec.volume_name = pv_name
                        new_c.status.phase = "Bound"
                        self.hub.update_pvc(new_c)
            except Unavailable:
                raise    # transport outage: degraded mode parks
            except Exception as e:  # noqa: BLE001 — surfaced as Status
                return Status.error(str(e), plugin=self.NAME)
            # API truth now holds the binding; drop the assumed overlay
            self.assume.restore(pv_name, pvc_key)
        return Status()
