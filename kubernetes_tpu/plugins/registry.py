"""In-tree plugin set: descriptors binding names to extension points,
device-kernel slots, events-to-register, and host implementations.

Equivalent of the reference's plugin registry
(/root/reference/pkg/scheduler/framework/plugins/registry.go:48-92), with
one structural difference: plugins whose Filter/Score is fused into the
device pipeline (models.pipeline) are DESCRIPTORS — their per-node logic
lives in ops/* kernels keyed by their FILTER_PLUGINS / SCORE_PLUGINS slot —
while queue/bind/lifecycle plugins are ordinary host classes implementing
the framework interfaces.

EventsToRegister sets mirror each reference plugin's EventsToRegister
(e.g. noderesources/fit.go:265, interpodaffinity/plugin.go:62,
podtopologyspread/plugin.go:139, nodeaffinity/node_affinity.go:89,
tainttoleration, nodeports, nodename, nodeunschedulable,
schedulinggates.go, defaultbinder/default_binder.go:52,
queuesort/priority_sort.go:44).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.hub import Fenced, Unavailable
from kubernetes_tpu.plugins import hints
from kubernetes_tpu.framework.interface import (
    ActionType,
    BindPlugin,
    ClusterEvent,
    ClusterEventWithHint,
    EventResource,
    PreEnqueuePlugin,
    QueueSortPlugin,
    Status,
)

A = ActionType
R = EventResource


def _ev(resource: R, action: A, hint=None) -> ClusterEventWithHint:
    return ClusterEventWithHint(event=ClusterEvent(resource, action),
                                queueing_hint_fn=hint)


@dataclass
class PluginDescriptor:
    """Metadata for one in-tree plugin."""

    name: str
    points: tuple[str, ...]
    default_weight: float = 0.0
    # slot names into pipeline.FILTER_PLUGINS / SCORE_PLUGINS when the
    # plugin's Filter/Score math runs on device
    device_filter: bool = False
    device_score: bool = False
    events: list[ClusterEventWithHint] = field(default_factory=list)
    # factory for plugins with host-side behavior (queue sort, gates, bind…)
    factory: Optional[Callable[[dict], object]] = None


class SchedulingGates(PreEnqueuePlugin):
    """Holds pods with non-empty spec.schedulingGates out of the activeQ
    (plugins/schedulinggates/scheduling_gates.go)."""

    NAME = "SchedulingGates"

    def pre_enqueue(self, pod: Pod) -> Status:
        if not pod.spec.scheduling_gates:
            return Status()
        gates = ", ".join(g.name for g in pod.spec.scheduling_gates)
        return Status.unschedulable(
            f"waiting for scheduling gates: {gates}",
            plugin=self.NAME, resolvable=False)


class PrioritySort(QueueSortPlugin):
    """(priority desc, queue-time asc) (queuesort/priority_sort.go:44)."""

    NAME = "PrioritySort"

    def less(self, a, b) -> bool:
        pa, pb = a.pod.priority(), b.pod.priority()
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp


class DefaultBinder(BindPlugin):
    """POSTs the Binding (defaultbinder/default_binder.go:52); the hub/client
    is injected by the scheduler."""

    NAME = "DefaultBinder"

    def __init__(self, binder: Optional[Callable[[Pod, str], None]] = None):
        self._binder = binder

    def bind(self, state, pod: Pod, node_name: str) -> Status:
        if self._binder is None:
            return Status.error("no binder client configured", self.NAME)
        try:
            self._binder(pod, node_name)
        except Unavailable:
            raise    # transport outage: degraded mode parks, not errors
        except Fenced:
            raise    # deposed epoch: the scheduler releases the claim
        except Exception as e:  # noqa: BLE001 — surfaced as Status
            return Status.error(str(e), self.NAME)
        return Status()


def _default_preemption_factory(args: dict):
    """Binds the PostFilter to the scheduler's Evaluator (injected via
    extra_args); absent outside a full scheduler (kernel tests)."""
    ev = args.get("preemption_evaluator")
    if ev is None:
        return None
    from kubernetes_tpu.framework.preemption import DefaultPreemption

    return DefaultPreemption(ev)


def in_tree_registry() -> dict[str, PluginDescriptor]:
    """name -> descriptor for every in-tree plugin (registry.go:48)."""
    pod_del = _ev(R.ASSIGNED_POD, A.DELETE | A.UPDATE_POD_SCALE_DOWN)
    node_alloc = _ev(R.NODE, A.ADD | A.UPDATE_NODE_ALLOCATABLE)
    descriptors = [
        PluginDescriptor(
            name="SchedulingGates", points=("pre_enqueue",),
            factory=lambda args: SchedulingGates(),
            # gated pods live in the queue's _gated pool and re-probe
            # PreEnqueue directly on gate events — queueing-hint fns are
            # never consulted for them, so no hint is registered here
            events=[_ev(R.POD,
                        A.UPDATE_POD_SCHEDULING_GATES_ELIMINATED)]),
        PluginDescriptor(
            name="PrioritySort", points=("queue_sort",),
            factory=lambda args: PrioritySort()),
        PluginDescriptor(
            name="NodeUnschedulable", points=("filter",), device_filter=True,
            events=[_ev(R.NODE, A.ADD | A.UPDATE_NODE_TAINT)]),
        PluginDescriptor(
            name="NodeName", points=("filter",), device_filter=True,
            events=[_ev(R.NODE, A.ADD)]),
        PluginDescriptor(
            name="TaintToleration", points=("filter", "score"),
            device_filter=True, device_score=True, default_weight=3,
            events=[_ev(R.NODE, A.ADD | A.UPDATE_NODE_TAINT,
                        hints.taint_toleration_hint)]),
        PluginDescriptor(
            name="NodeAffinity", points=("filter", "score"),
            device_filter=True, device_score=True, default_weight=2,
            events=[_ev(R.NODE, A.ADD | A.UPDATE_NODE_LABEL,
                        hints.node_affinity_hint)]),
        PluginDescriptor(
            name="NodePorts", points=("filter",), device_filter=True,
            events=[_ev(R.ASSIGNED_POD, A.DELETE,
                        hints.node_ports_hint),
                    _ev(R.NODE, A.ADD | A.UPDATE_NODE_ALLOCATABLE,
                        hints.node_ports_hint)]),
        PluginDescriptor(
            name="NodeResourcesFit", points=("filter", "score"),
            device_filter=True, device_score=True, default_weight=1,
            events=[_ev(R.ASSIGNED_POD,
                        A.DELETE | A.UPDATE_POD_SCALE_DOWN,
                        hints.fit_hint),
                    _ev(R.NODE, A.ADD | A.UPDATE_NODE_ALLOCATABLE,
                        hints.fit_hint)]),
        PluginDescriptor(
            name="PodTopologySpread", points=("filter", "score"),
            device_filter=True, device_score=True, default_weight=2,
            events=[_ev(R.ASSIGNED_POD,
                        A.ADD | A.DELETE | A.UPDATE_POD_LABEL,
                        hints.topology_spread_hint),
                    _ev(R.NODE, A.ADD | A.DELETE | A.UPDATE_NODE_LABEL
                        | A.UPDATE_NODE_TAINT,
                        hints.topology_spread_hint)]),
        PluginDescriptor(
            name="InterPodAffinity", points=("filter", "score"),
            device_filter=True, device_score=True, default_weight=2,
            events=[_ev(R.ASSIGNED_POD,
                        A.ADD | A.DELETE | A.UPDATE_POD_LABEL,
                        hints.inter_pod_affinity_hint),
                    _ev(R.NODE, A.ADD | A.UPDATE_NODE_LABEL,
                        hints.inter_pod_affinity_hint)]),
        PluginDescriptor(
            name="NodeResourcesBalancedAllocation", points=("score",),
            device_score=True, default_weight=1,
            events=[pod_del, node_alloc]),
        PluginDescriptor(
            name="ImageLocality", points=("score",), device_score=True,
            default_weight=1,
            events=[_ev(R.NODE, A.ADD | A.UPDATE_NODE_LABEL)]),
        # learned MLP score term (ops/learned.py), fused into the same
        # launch; OFF by default — a profile opts in at the score point
        # and names its checkpoint in plugin_config. The factory builds
        # the host-side checkpoint manager (plugins/learned.py), which
        # is NOT a host ScorePlugin: scoring stays on device
        PluginDescriptor(
            name="LearnedScore", points=("score",), device_score=True,
            default_weight=1,
            factory=_learned_factory),
        PluginDescriptor(
            name="DefaultPreemption", points=("post_filter", "pre_enqueue"),
            factory=_default_preemption_factory,
            events=[_ev(R.ASSIGNED_POD, A.DELETE)]),
        PluginDescriptor(
            name="DefaultBinder", points=("bind",),
            factory=lambda args: DefaultBinder(args.get("binder"))),
        # gang scheduling: PreFilter capacity bound + Permit quorum
        # assembly + unreserve-driven atomic rollback (plugins/gang.py);
        # the shared coordinator is injected by the scheduler
        PluginDescriptor(
            name="GangScheduling", points=("filter", "reserve", "permit"),
            factory=lambda args: args.get("gang_shared"),
            events=[_ev(R.POD_GROUP, A.ADD | A.UPDATE),
                    # ADD: a peer's bind advances a parked member's
                    # quorum (the permit-timeout retry path after
                    # failover); DELETE: freed capacity + shrunk gangs
                    _ev(R.ASSIGNED_POD, A.ADD | A.DELETE),
                    _ev(R.NODE, A.ADD | A.UPDATE_NODE_ALLOCATABLE)]),
        # --- volume family: host Filter plugins (plugins/volume.py) ---
        PluginDescriptor(
            name="VolumeZone", points=("filter",),
            factory=_volume_factory("VolumeZone"),
            events=[_ev(R.PV, A.ADD | A.UPDATE,
                        hints.volume_binding_hint),
                    _ev(R.PVC, A.ADD | A.UPDATE,
                        hints.volume_binding_hint),
                    _ev(R.NODE, A.ADD | A.UPDATE_NODE_LABEL),
                    _ev(R.STORAGE_CLASS, A.ADD)]),
        PluginDescriptor(
            name="VolumeRestrictions", points=("filter",),
            factory=_volume_factory("VolumeRestrictions"),
            events=[_ev(R.ASSIGNED_POD, A.DELETE,
                        hints.volume_restrictions_hint),
                    _ev(R.PVC, A.ADD | A.UPDATE,
                        hints.volume_restrictions_hint)]),
        PluginDescriptor(
            name="NodeVolumeLimits", points=("filter",),
            factory=_volume_factory("NodeVolumeLimits"),
            events=[_ev(R.CSI_NODE, A.ADD | A.UPDATE),
                    _ev(R.ASSIGNED_POD, A.DELETE,
                        hints.node_volume_limits_hint),
                    _ev(R.PVC, A.ADD),
                    _ev(R.PV, A.ADD)]),
        PluginDescriptor(
            name="DynamicResources",
            points=("filter", "reserve", "pre_bind"),
            factory=_dra_factory,
            events=[_ev(R.RESOURCE_CLAIM, A.ADD | A.UPDATE | A.DELETE,
                        hints.dra_hint),
                    _ev(R.RESOURCE_SLICE, A.ADD | A.DELETE,
                        hints.dra_hint),
                    _ev(R.NODE, A.ADD)]),
        PluginDescriptor(
            name="VolumeBinding",
            points=("filter", "score", "reserve", "pre_bind"),
            default_weight=1,
            factory=_volume_factory("VolumeBinding"),
            events=[_ev(R.PVC, A.ADD | A.UPDATE,
                        hints.volume_binding_hint),
                    _ev(R.PV, A.ADD | A.UPDATE,
                        hints.volume_binding_hint),
                    _ev(R.NODE, A.ADD | A.UPDATE_NODE_LABEL
                        | A.UPDATE_NODE_TAINT),
                    _ev(R.STORAGE_CLASS, A.ADD,
                        hints.volume_binding_hint),
                    _ev(R.CSI_STORAGE_CAPACITY, A.ADD | A.UPDATE,
                        hints.volume_binding_hint),
                    _ev(R.ASSIGNED_POD, A.DELETE)]),
    ]
    return {d.name: d for d in descriptors}


def _dra_factory(args: dict):
    hub = args.get("hub")
    if hub is None:
        return None
    # ONE instance per scheduler, shared across profiles (the reference's
    # SharedDRAManager, scheduler.go:311-333): the assume overlay must see
    # every profile's in-flight allocations or two same-batch pods from
    # different profiles could double-book a device
    shared = args.get("dra_shared")
    if shared is not None:
        return shared
    from kubernetes_tpu.plugins.dra import DynamicResources

    return DynamicResources(hub)


def _learned_factory(args: dict):
    from kubernetes_tpu.plugins.learned import LearnedScore

    return LearnedScore(args)


def _volume_factory(name: str):
    """Volume plugins need the hub (API views); absent outside a full
    scheduler (kernel tests) the plugin is skipped."""
    def make(args: dict):
        hub = args.get("hub")
        if hub is None:
            return None
        from kubernetes_tpu.plugins import volume

        return getattr(volume, name)(hub)
    return make


DEVICE_FILTER_PLUGINS = tuple(
    d.name for d in in_tree_registry().values() if d.device_filter)
DEVICE_SCORE_PLUGINS = tuple(
    d.name for d in in_tree_registry().values() if d.device_score)
