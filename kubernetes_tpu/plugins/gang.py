"""GangScheduling: all-or-nothing admission for PodGroups.

The plugin half of the gang subsystem (the queue half is
backend/jobqueue.py). Three extension points on the existing framework:

* **PreFilter** — rejects members of a gang whose remaining
  ``min_member`` provably cannot fit anywhere. The bound itself comes
  from the device: for gangs the fused packer handled, the packer's own
  capacity reduction lands in the memo (``note_device_cap``); for
  host-path gangs the reduction is dispatched ASYNC
  (``ops.gang.gang_capacity_device``) and its D2H pull rides the
  scheduler's existing one-per-cycle ``device_get`` — PreFilter answers
  from the memo and returns SKIP (optimistic, one attempt of lag) while
  a fresh bound is still in flight. No blocking pull, ever.

* **Permit** — the transactional commit point of the HOST-FALLBACK
  path (gangs the device packer cannot express: topology terms,
  heterogeneous members, claims/volumes, preemption). Each member that
  clears Reserve WAITs in the framework's wait room (its node
  reservation held as an assumed pod) until ``min_member`` members have
  reserved; the member that completes the quorum allows every waiting
  peer, and all of them proceed to the fenced binder together. A
  timeout or any member's failure rolls back EVERY reservation
  atomically via ``unreserve`` — no partial gang ever occupies nodes.
  Gangs placed by the device packer bypass the quorum: the scheduler
  marks them ``device_admit``-ed (the all-or-nothing device verdict IS
  the quorum) and Permit answers allow immediately.

* **Reserve/Unreserve** — the rollback hook: an unreserved member of an
  assembling gang rejects all waiting peers, whose harvest unreserves
  them in turn (re-entry is cut by popping the assembly state first).

The coordinator instance is shared across profiles (like the DRA
manager) via the scheduler's ``gang_shared`` extra arg; the scheduler
feeds it PodGroup watch events, bound-member observations from the
informer, and poison marks from the quarantine (a poisoned member
poisons the whole gang).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from kubernetes_tpu.api.objects import (
    LABEL_POD_GROUP,
    Pod,
    PodGroup,
    pod_group_key,
)
from kubernetes_tpu.api.resources import pod_request
from kubernetes_tpu.framework.interface import (
    Code,
    FilterPlugin,
    PermitPlugin,
    PreFilterPlugin,
    ReservePlugin,
    Status,
)

logger = logging.getLogger("kubernetes_tpu.gang")

# a gang key whose PodGroup is missing from the local cache re-probes the
# hub at most this often — the watch feed (set_group) is the real source;
# per-scheduling-attempt RPCs from the plugin hot path would hammer a
# RemoteHub for every member of a deleted group still in the queue
GROUP_PROBE_INTERVAL_S = 5.0


class GangScheduling(PreFilterPlugin, FilterPlugin, ReservePlugin,
                     PermitPlugin):
    """The gang coordinator + its framework plugin faces."""

    NAME = "GangScheduling"

    def __init__(self, hub=None,
                 mirror_fn: Optional[Callable] = None,
                 now: Callable[[], float] = time.time):
        self.hub = hub
        self._mirror_fn = mirror_fn
        self._now = now
        self.metrics = None                 # SchedulerMetrics, wired late
        self._groups: dict[str, PodGroup] = {}
        # the per-profile wait rooms this coordinator can reach into
        # (registered by the scheduler; one per Framework)
        self._waiting_maps: list = []
        # gang key -> {"waiting": set(uid), "deadline": float}
        self._assembling: dict[str, dict] = {}
        # gang key -> uids of members the informer has seen BOUND (quorum
        # counting must survive failover: a new leader admits the tail of
        # a half-bound gang instead of re-demanding min_member fresh)
        self._bound: dict[str, set[str]] = {}
        # gang key -> {offending uid -> reason}, while members sit in
        # poison quarantine (refcounted: the gang releases only when its
        # LAST quarantined member is released/deleted)
        self._poisoned: dict[str, dict[str, str]] = {}
        # gang key -> earliest next hub probe for a missing PodGroup
        self._group_probe: dict[str, float] = {}
        # PreFilter capacity-bound memo: gang key -> (token, cap). The
        # bound's inputs are identical for every same-shaped member of a
        # gang within one mirror sync, so one device reduction serves
        # the whole gang's batch. Fed by the device packer's cap column
        # (note_device_cap) or by an ASYNC reduction whose D2H pull the
        # scheduler folds into its per-cycle device_get (_pending_caps)
        self._cap_cache: dict[str, tuple] = {}
        # gang key -> (token, device scalar) awaiting the next cycle's
        # pull; resolved by Scheduler._finish / the gang dispatch
        self._pending_caps: dict[str, tuple] = {}
        # gang key -> uids admitted by the device packer's all-or-nothing
        # verdict: Permit allows them without quorum assembly (the
        # verdict IS the quorum); cleared when the unit's commit ends
        self._device_admitted: dict[str, set[str]] = {}
        self.stats = {"admitted": 0, "timeouts": 0, "rollbacks": 0,
                      "device_admitted": 0}

    # ------------- scheduler-side wiring -------------

    def register_waiting_map(self, waiting_map) -> None:
        if waiting_map not in self._waiting_maps:
            self._waiting_maps.append(waiting_map)

    def set_group(self, group: PodGroup) -> None:
        self._groups[group.key()] = group
        self._group_probe.pop(group.key(), None)

    def group_of(self, key: str) -> Optional[PodGroup]:
        return self._groups.get(key)

    def remove_group(self, key: str) -> None:
        self._groups.pop(key, None)
        self._assembling.pop(key, None)
        self._bound.pop(key, None)
        self._poisoned.pop(key, None)
        self._cap_cache.pop(key, None)
        self._pending_caps.pop(key, None)
        self._device_admitted.pop(key, None)

    def note_bound(self, pod: Pod) -> None:
        key = pod_group_key(pod)
        if key is not None:
            self._bound.setdefault(key, set()).add(pod.metadata.uid)
            # a peer's confirmed bind can complete a WAITING member's
            # quorum (post-failover: the new leader reserves the tail
            # member before its informer has confirmed every old bind) —
            # without this re-check the member would sit out its permit
            # timeout and park with no event left to wake it
            self._maybe_complete(key)

    # ------------- device-packer wiring -------------

    def cap_token(self, mirror, pod: Pod) -> tuple:
        """The capacity memo's freshness token: the bound only changes
        when the free matrix's CONTENT changes or the request shape
        differs (content-keyed so a reserve/rollback wave that returns
        free to identical bytes keeps the memo — see
        Mirror.free_fingerprint)."""
        row = mirror._res_row(pod_request(pod))
        return (mirror.free_fingerprint(), row.tobytes())

    def note_device_cap(self, key: str, token: tuple, cap: int) -> None:
        """The fused packer's capacity column for this gang (pulled with
        its verdict): seed the PreFilter memo so the host-fallback bound
        never re-derives what the packer already computed."""
        self._cap_cache[key] = (token, int(cap))
        self._pending_caps.pop(key, None)

    def take_pending_caps(self) -> list[tuple]:
        """(key, token, device scalar) entries awaiting resolution —
        the scheduler appends the scalars to its one-per-cycle
        device_get and hands the values back via resolve_cap."""
        return [(key, token, arr)
                for key, (token, arr) in self._pending_caps.items()]

    def resolve_cap(self, key: str, token: tuple, cap: int) -> None:
        pend = self._pending_caps.get(key)
        if pend is not None and pend[0] == token:
            del self._pending_caps[key]
        self._cap_cache[key] = (token, int(cap))

    def device_admit(self, key: str, uids: set) -> None:
        """Mark a unit the device packer placed: Permit allows these
        members without quorum assembly (all-or-nothing was already
        proven in one launch)."""
        self._device_admitted[key] = set(uids)

    def clear_device_admit(self, key: str) -> None:
        self._device_admitted.pop(key, None)

    def bound_count(self, key: str) -> int:
        """Informer-confirmed bound members of this gang — the single
        bound-member registry; the job queue's min_member gating queries
        it instead of keeping its own copy that could drift."""
        return len(self._bound.get(key, ()))

    def note_unbound(self, pod: Pod) -> None:
        key = pod_group_key(pod)
        if key is not None:
            members = self._bound.get(key)
            if members is not None:
                members.discard(pod.metadata.uid)
                if not members:
                    del self._bound[key]

    def poison(self, key: str, reason: str, uid: str = "") -> None:
        """A member of this gang was quarantined: the whole gang is held
        out (members reject at Reserve/PreFilter) and any assembling
        reservation rolls back — a gang scheduled around its poisoned
        member would violate all-or-nothing."""
        self._poisoned.setdefault(key, {})[uid] = reason
        self._rollback(key, f"gang member quarantined: {reason}",
                       timeout=False)

    def release_poison(self, key: str, uid: str = "") -> None:
        """One quarantined member released/deleted: the gang unpoisons
        only when NO member remains in quarantine."""
        members = self._poisoned.get(key)
        if members is None:
            return
        members.pop(uid, None)
        if not members:
            del self._poisoned[key]

    def _poison_reason(self, key: str) -> Optional[str]:
        members = self._poisoned.get(key)
        if not members:
            return None
        return next(iter(members.values()))

    def poisoned_gangs(self) -> dict[str, str]:
        return {k: next(iter(v.values()))
                for k, v in self._poisoned.items() if v}

    # ------------- relevance gates -------------

    @staticmethod
    def applies(pod: Pod) -> bool:
        return LABEL_POD_GROUP in pod.metadata.labels

    def _state_of(self, pod: Pod) -> tuple[Optional[str],
                                           Optional[PodGroup],
                                           Optional[Status]]:
        key = pod_group_key(pod)
        if key is None:
            return None, None, None
        reason = self._poison_reason(key)
        if reason is not None:
            return key, None, Status.unschedulable(
                f"gang {key} quarantined: {reason}", plugin=self.NAME)
        group = self._groups.get(key)
        if group is None and self.hub is not None \
                and self._group_probe.get(key, 0.0) <= self._now():
            try:
                group = self.hub.get_pod_group(pod.metadata.namespace,
                                               pod.metadata.labels[
                                                   LABEL_POD_GROUP])
            except Exception:  # noqa: BLE001 — hub outage: park, don't
                group = None   # poison the batch from a plugin raise
            if group is not None:
                self._groups[key] = group
                self._group_probe.pop(key, None)
            else:
                self._group_probe[key] = (self._now()
                                          + GROUP_PROBE_INTERVAL_S)
        if group is None:
            return key, None, Status.unschedulable(
                f"waiting for PodGroup {key}", plugin=self.NAME)
        return key, group, None

    # ------------- PreFilter: cheap impossibility check -------------

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        key, group, bad = self._state_of(pod)
        if key is None:
            return Status.skip()
        if bad is not None:
            return bad
        # remaining members to PLACE: bound peers and peers already
        # reserved (waiting at Permit) both count — the waiters' node
        # reservations have already left free_matrix, so charging the
        # full min_member against what's left would livelock a gang
        # that exactly fits but spans scheduling batches
        st = self._assembling.get(key)
        reserved = len(st["waiting"]) if st is not None else 0
        need = max(group.min_member - len(self._bound.get(key, ()))
                   - reserved, 1)
        mirror = self._mirror_fn() if self._mirror_fn else None
        # the FREE-capacity bound is only provable impossibility for a
        # gang that cannot preempt: a positive-priority gang may open
        # capacity by evicting lower-priority pods (whole lower gangs via
        # the evaluator), so it must reach PostFilter, not park here
        if mirror is not None and pod.priority() <= 0:
            # one reduction per gang per mirror sync, not per member:
            # the token pins the memo to this request shape and blob
            # state (free_matrix only changes at mirror.sync). The memo
            # is fed by the device packer's cap column or by an ASYNC
            # reduction pulled with the scheduler's per-cycle
            # device_get — a memo miss answers SKIP (optimistic) while
            # the fresh bound is in flight, never a blocking pull
            token = self.cap_token(mirror, pod)
            cached = self._cap_cache.get(key)
            if cached is not None and cached[0] == token:
                if cached[1] < need:
                    return Status.unschedulable(
                        f"gang {key}: cluster capacity bound {cached[1]} "
                        f"< min_member remainder {need}", plugin=self.NAME)
            else:
                pend = self._pending_caps.get(key)
                if pend is None or pend[0] != token:
                    from kubernetes_tpu.ops.gang import (
                        gang_capacity_device,
                    )

                    self._pending_caps[key] = (token, gang_capacity_device(
                        mirror.free_matrix(),
                        mirror._res_row(pod_request(pod))))
        return Status.skip()    # skip => the per-node filter never runs

    def filter(self, state, pod: Pod, node_info) -> Status:
        return Status()         # unreachable: pre_filter always skips

    # ------------- Reserve / the rollback hook -------------

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        key, _group, bad = self._state_of(pod)
        if key is None:
            return Status()
        return bad if bad is not None else Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        """A gang member's reservation was undone (permit timeout, permit
        rejection, reserve failure of a later plugin, pod deletion):
        roll back the rest of the assembling gang."""
        key = pod_group_key(pod)
        if key is None:
            return
        st = self._assembling.get(key)
        if st is None:
            return              # gang already admitted (or rolled back)
        uid = pod.metadata.uid
        in_gang = uid in st["waiting"]
        st["waiting"].discard(uid)
        if in_gang:
            timed_out = self._now() >= st["deadline"]
            self._rollback(key, "gang member "
                           f"{pod.key()} unreserved; rolling back gang",
                           timeout=timed_out)

    def _rollback(self, key: str, msg: str, timeout: bool) -> None:
        st = self._assembling.pop(key, None)
        if st is None:
            return              # nothing assembling (already rolled back)
        self.stats["rollbacks"] += 1
        if timeout:
            self.stats["timeouts"] += 1
        m = self.metrics
        if m is not None:
            m.gang_rollbacks.inc()
            if timeout:
                m.gang_timeouts.inc()
        logger.info("gang %s rollback (%s waiting): %s",
                    key, len(st["waiting"]), msg)
        for uid in list(st["waiting"]):
            for wmap in self._waiting_maps:
                wp = wmap.get(uid)
                if wp is not None:
                    wp.reject(self.NAME, msg)
                    break

    # ------------- Permit: quorum assembly -------------

    def permit(self, state, pod: Pod, node_name: str
               ) -> tuple[Status, float]:
        key, group, bad = self._state_of(pod)
        if key is None:
            return Status.skip(), 0.0
        da = self._device_admitted.get(key)
        if da is not None and pod.metadata.uid in da:
            # placed by the fused device packer: the all-or-nothing
            # verdict already proved the whole unit fits — no quorum
            # assembly, straight to the fenced binder
            return Status(), 0.0
        if bad is not None:
            return bad, 0.0
        now = self._now()
        st = self._assembling.get(key)
        if st is None:
            st = self._assembling[key] = {
                "waiting": set(),
                "deadline": now + max(group.schedule_timeout_seconds, 0.1)}
        quorum = (len(st["waiting"]) + 1
                  + len(self._bound.get(key, ())))
        if quorum >= max(group.min_member, 1):
            # quorum reached: this member completes the gang — allow
            # every waiting peer; all proceed to the binding cycle
            self._admit(key, st)
            return Status(), 0.0
        st["waiting"].add(pod.metadata.uid)
        remaining = max(st["deadline"] - now, 0.1)
        return Status(code=Code.WAIT, plugin=self.NAME), remaining

    def _admit(self, key: str, st: dict) -> None:
        waiting = st["waiting"]
        self._assembling.pop(key, None)
        for uid in waiting:
            for wmap in self._waiting_maps:
                wp = wmap.get(uid)
                if wp is not None:
                    wp.allow(self.NAME)
                    break
        self.stats["admitted"] += 1
        if self.metrics is not None:
            self.metrics.gang_admitted.inc()

    def _maybe_complete(self, key: str) -> None:
        """Informer-driven quorum re-check: waiting members + confirmed
        bound members may now satisfy min_member."""
        st = self._assembling.get(key)
        group = self._groups.get(key)
        if st is None or group is None or not st["waiting"]:
            return
        quorum = len(st["waiting"]) + len(self._bound.get(key, ()))
        if quorum >= max(group.min_member, 1):
            self._admit(key, st)

    # ------------- introspection -------------

    def debug_state(self) -> dict:
        return {
            "assembling": {
                key: {"waiting": len(st["waiting"]),
                      "deadline": st["deadline"]}
                for key, st in self._assembling.items()},
            "bound_members": {k: len(v) for k, v in self._bound.items()},
            "poisoned": self.poisoned_gangs(),
            "pending_caps": len(self._pending_caps),
            "stats": dict(self.stats),
        }
