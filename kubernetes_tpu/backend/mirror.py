"""Host->HBM mirror: packs the generation-diffed snapshot into dense blobs.

This is the TPU-native replacement for the reference's incremental snapshot
refresh (cache.go:186 UpdateSnapshot): instead of cloning Go NodeInfo structs,
we re-pack only *changed* node rows (generation diff) directly into dense
numpy blob buffers (one f32 + one i32 per struct kind, see ops.blobs) and ship
at most three arrays to the device per cycle. Each node keeps a stable row
index for its lifetime; scheduled pods occupy slots of a device pod table used
by inter-pod-affinity / topology-spread kernels.

All strings are interned (utils.interner); set-valued fields are padded to
the static Capacities. Over-capacity conditions raise CapacityError — the
caller re-buckets (doubles the capacity and re-packs, which recompiles the
kernels once per bucket).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from kubernetes_tpu.api.labels import (
    label_selector_matches,
    requirements_match,
    selector_requirements,
)
from kubernetes_tpu.api.objects import (
    Affinity,
    Pod,
    PodAffinityTerm,
)
from kubernetes_tpu.api.resources import Resource
from kubernetes_tpu.backend.node_info import NodeInfo, PodInfo
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.ops import features as F
from kubernetes_tpu.ops.features import (
    Capacities,
    ClusterBlobs,
    ClusterTensors,
    PodBlobs,
    PodFeatures,
    codecs,
    unpack_cluster,
    unpack_pods,
)
from kubernetes_tpu.utils.interner import NONE, Interner

MI = 1024 * 1024

# taint the node controller applies for spec.unschedulable; the
# NodeUnschedulable plugin simulates tolerating it (plugins/nodeunschedulable)
TAINT_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

# ---- PodFeatures field groups for subset transfers (pod_fields) ----
# fields every launch reads (fit, tie-break, unschedulable-taint simulation,
# NodeName, the commit scan/auction carries)
POD_CORE_FIELDS = (
    "valid", "req", "nonzero_req", "num_containers", "priority",
    "ns", "name_id", "uid_id", "nominated_row", "node_name_id",
    "tol_valid", "tol_key", "tol_op", "tol_val", "tol_effect",
)
# per active-feature additions (Mirror.launch_features)
POD_FEATURE_FIELDS = {
    "images": ("image_ids",),
    "ports": ("hp_ip", "hp_proto", "hp_port"),
    "nodeaffinity": (
        "aff_pin", "nodesel_cols", "nodesel_vals", "sel_term_valid",
        "sel_col", "sel_op", "sel_is_field", "sel_vals", "sel_num",
        "pref_weight", "pref_col", "pref_op", "pref_is_field", "pref_vals",
        "pref_num"),
    # pin-only batches (daemonset shape): ONE i32 per pod instead of the
    # 14 selector/preferred arrays — the kernels compile to a [N] compare
    "nodeaffinity_pin": ("aff_pin",),
}
# everything the topology kernels read (enable_topology launches)
POD_TOPO_FIELDS = (
    "plabel_vals", "aff_self_match",
    "tsc_tk", "tsc_max_skew", "tsc_hard", "tsc_min_domains",
    "tsc_sel_cols", "tsc_sel_ops", "tsc_sel_vals",
    "tsc_honor_affinity", "tsc_honor_taints",
) + tuple(
    f"{g}_{suffix}"
    for g in ("aff", "anti", "paff", "panti")
    for suffix in ("tk", "ns", "ns_all", "sel_cols", "sel_ops", "sel_vals")
) + ("paff_weight", "panti_weight")

_unpack_cluster_jit = jax.jit(unpack_cluster, static_argnums=1)


def _f32_ceil(x) -> np.float32:
    """Smallest float32 >= x (x exact in float64 for byte values < 2^53:
    /MI is a power-of-two scale). Demand rounds UP. Comparisons go
    through python float: NEP-50 weak promotion would otherwise demote
    x to float32 and hide the rounding error being tested for."""
    v = np.float32(x)
    return v if float(v) >= float(x) else np.nextafter(v,
                                                       np.float32(np.inf))


def _f32_floor(x) -> np.float32:
    """Largest float32 <= x. Capacity rounds DOWN."""
    v = np.float32(x)
    return v if float(v) <= float(x) else np.nextafter(v,
                                                       np.float32(-np.inf))


def _round_row_f32(row64: np.ndarray, up: bool) -> np.ndarray:
    """Vectorized directed f32 rounding of a float64 row (the scalar
    helpers per column were measurable on the pod-commit fast path)."""
    v = row64.astype(np.float32)
    back = v.astype(np.float64)
    m = (back < row64) if up else (back > row64)
    if m.any():
        v[m] = np.nextafter(v[m],
                            np.float32(np.inf) if up
                            else np.float32(-np.inf))
    return v
_unpack_pods_jit = jax.jit(unpack_pods, static_argnums=1)


def _scatter_rows(buf, idx, rows):
    return buf.at[idx].set(rows)


# donate the resident buffer: the update happens in place on device
_scatter_rows_jit = jax.jit(_scatter_rows, donate_argnums=(0,))


import dataclasses


@dataclasses.dataclass
class LaunchSpec:
    """Everything one schedule_batch launch needs (Mirror.prepare_launch).
    ``enable_topology``/``d_cap``/``active``/``pfields`` are the STATIC
    launch args; ``ptmpl`` is the device-resident template backing the
    subset pod blobs."""

    cblobs: ClusterBlobs
    pblobs: PodBlobs
    enable_topology: bool
    d_cap: int
    active: tuple[str, ...]
    pfields: tuple[str, ...]
    ptmpl: PodBlobs
    # topology dedup groups (see pipeline: group-level topology statics).
    # gid [B] i32: per-pod group id; rep [G_cap] i32: representative pod row
    # per group (padded); g_cap: static pow2 group-count bucket.
    gid: jnp.ndarray | None = None
    rep: jnp.ndarray | None = None
    g_cap: int = 0
    # batched DRA allocator inputs (ops.dra.DraBatch), attached by the
    # Scheduler after prepare_launch when the batch carries device-routed
    # claim pods; None compiles the DRA kernel out of the launch
    dra: object | None = None
    # SOFT-ONLY topology launch: enable_topology is on but no batch pod
    # carries a required (anti)affinity term or a DoNotSchedule spread
    # constraint — soft terms are scores, not constraints, so the caller
    # may run the parallel auction with the fused soft-score terms
    # (pipeline._soft_statics) instead of the serial commit scan
    topo_soft: bool = False


class CapacityError(Exception):
    """A padded capacity was exceeded; caller should re-bucket (double the
    capacity and re-pack; kernels recompile once per bucket)."""

    def __init__(self, field: str, needed: int):
        super().__init__(f"capacity exceeded for {field}: need {needed}")
        self.field = field
        self.needed = needed



# phase-1 dedup group bucket for no-topology launches (prepare_launch):
# FIXED so the static g_cap jit key never varies with batch composition
P1_DEDUP_GROUP_CAP = 8

# bucket hysteresis (ISSUE 15): the topology DOMAIN bucket (a static
# jit arg) EXPANDS immediately on demand but only SHRINKS after this
# many consecutive launches needed at most half of it — and the
# high-water mark survives capacity re-buckets (adopt_hysteresis), so
# an oscillating cluster size (churn recreating nodes around a growth
# boundary) stops minting fresh compiled shapes every swing
# (scheduler_device_compiles_total{cause=rebucket|topology_bucket}
# stays flat across the oscillation).
BUCKET_DECAY_LAUNCHES = 64


class Mirror:
    def __init__(self, interner: Interner | None = None,
                 caps: Capacities = Capacities(), mesh=None):
        self.caps = caps
        # multi-chip: shard the resident node table over the mesh's 'nodes'
        # axis (SURVEY §5.7 — the node axis is what outgrows one chip's
        # HBM). Every launch consuming to_blobs() then runs SPMD over the
        # mesh with no further plumbing: jit partitions the program from
        # the operand shardings, reductions become ICI collectives.
        self.mesh = mesh
        self._dev_sharding: dict[str, object] = {}
        self._scatter_fns: dict[str, object] = {}
        if mesh is not None:
            from kubernetes_tpu.parallel import mirror_shardings

            self._dev_sharding = mirror_shardings(mesh)
            for key, sh in self._dev_sharding.items():
                # pin the scatter output to the resident sharding so the
                # incremental path can never drift the buffer to a layout
                # the launch programs weren't compiled for
                self._scatter_fns[key] = jax.jit(
                    _scatter_rows, donate_argnums=(0,), out_shardings=sh)
        self.interner = interner or Interner()
        self.node_codec, self.table_codec, self.pod_codec = codecs(caps)
        self.node_f32, self.node_i32 = self.node_codec.alloc(caps.nodes)
        _, self.pods_i32 = self.table_codec.alloc(caps.pods)
        self._row_of: dict[str, int] = {}        # node name -> row
        self._row_gen: dict[str, int] = {}       # node name -> packed generation
        self._free_rows: list[int] = list(range(caps.nodes - 1, -1, -1))
        self._ext_index: dict[str, int] = {}     # extended resource -> column
        # columnized node labels: key string -> column
        self._label_col: dict[str, int] = {}
        # columnized pod labels (separate key space from node labels)
        self._pod_label_col: dict[str, int] = {}
        # topology keys in use by any term/constraint: key -> tk index, with
        # per-tk compact domain ids (value id -> dense domain index) and the
        # raw node labels per row for backfilling when a NEW topology key
        # registers after nodes were already packed (rare: hostname/zone/
        # region are pre-registered below)
        self._topo_col: dict[str, int] = {}
        self._tk_key: list[str] = []
        self._tk_domains: list[dict[int, int]] = []
        self._row_node_labels: dict[int, dict[str, str]] = {}
        # topo keys referenced by any packed term/constraint (batch or table):
        # bounds the domain scatter space a launch actually needs
        self._used_tks: set[int] = set()
        self._uids_with_terms: set[str] = set()  # table pods carrying terms
        # namespace store (name -> labels) for unrolling namespaceSelectors;
        # table pods whose terms carry a non-empty namespaceSelector repack
        # when the namespace set changes (sync checks ns_generation)
        self._namespaces: dict[str, dict[str, str]] = {}
        self._ns_gen = 0
        self._uids_with_nssel: set[str] = set()
        # nominated (preemptor) pods: packed per-cycle by set_nominated under
        # "nominated:<uid>" keys; per-row reserved request sums
        self._nominated_uids: set[str] = set()
        self._nominated_req_of_row: dict[int, np.ndarray] = {}
        self._pod_tmpl: tuple[np.ndarray, np.ndarray] | None = None
        self._pod_tmpl_dev = None          # device push of _pod_template
        self._subset_tmpl: dict[tuple, tuple[np.ndarray, np.ndarray]] = {}
        # plain-pod packed-row cache: fields-tuple -> content-key -> row
        self._plain_rows: dict[tuple, dict] = {}
        self._table_i32_tmpl: np.ndarray | None = None
        self._row_node_obj: dict[int, object] = {}  # row -> packed Node obj
        # workload-activity tracking for launch_features(): which rows carry
        # taints / used host ports / images — a feature absent cluster-wide
        # AND batch-wide compiles out of the launch entirely
        self._rows_with_taints: set[int] = set()
        self._rows_with_ports: set[int] = set()
        self._rows_with_images: set[int] = set()
        # every namespace any packed pod lives in: selectors are evaluated
        # over store ∪ pod namespaces (labels default {}), matching the
        # reference's nil-nsLabels behavior for namespaces that have no
        # Namespace object (AffinityTerm.Matches with empty labels.Set)
        self._known_pod_ns: set[str] = set()
        self._pod_slot: dict[str, int] = {}      # pod uid -> pod-table slot
        self._node_pods: dict[str, dict[str, int]] = {}  # node -> uid -> slot
        # uid -> packed Pod object, held strongly so identity comparison is a
        # sound change detector (a bare id() could be reused after GC)
        self._pod_obj: dict[str, Pod] = {}
        self._node_of_pod: dict[str, str] = {}   # uid -> node name
        self._free_slots: list[int] = list(range(caps.pods - 1, -1, -1))
        self._row_names: list[str | None] = [None] * caps.nodes
        # domain-bucket hysteresis high-water mark + decay counter (see
        # BUCKET_DECAY_LAUNCHES); survives re-bucketing via
        # adopt_hysteresis so a fresh mirror doesn't re-learn it
        self._d_hw = 0
        self._d_low = 0
        # incremental device-mirror dirty tracking: per-row/slot sets feed a
        # scatter-update of the resident HBM buffers (the row-level analog of
        # the reference's generation-diffed UpdateSnapshot, cache.go:186);
        # the bool flags force a full re-upload (first sync, topo backfill)
        self._dirty_full = {"node": True, "pods": True}
        self._dirty_rows: set[int] = set()
        self._dirty_slots: set[int] = set()
        self._dev: dict[str, jax.Array] = {}
        self._last_sync: tuple[int, int] | None = None
        # (last_sync, hash) memo behind free_fingerprint()
        self._free_fp: tuple | None = None
        # stable well-known ids, interned up front
        self.wk_unschedulable_key = self._i(TAINT_UNSCHEDULABLE)
        self.wk_wildcard_ip = self._i("0.0.0.0")
        # pre-register the ubiquitous topology keys so backfill never runs
        # for them (LABEL_HOSTNAME/ZONE/REGION, api.objects)
        for key in ("kubernetes.io/hostname", "topology.kubernetes.io/zone",
                    "topology.kubernetes.io/region"):
            self.topo_col(key)

    def well_known(self) -> dict[str, jnp.ndarray]:
        return {
            "unschedulable_taint_key": jnp.int32(self.wk_unschedulable_key),
            "wildcard_ip": jnp.int32(self.wk_wildcard_ip),
        }

    # ------------- interning helpers -------------

    def _i(self, s: str) -> int:
        # ids are unbounded: no device-side vocab table exists (numeric label
        # values ride the per-node label_nums column instead)
        return self.interner.intern(s)

    def label_col(self, key: str) -> int:
        """Register (or fetch) the label column for a node-label key.
        Only NODES register columns; pods resolve with label_col_lookup."""
        col = self._label_col.get(key)
        if col is None:
            if len(self._label_col) >= self.caps.label_cols:
                raise CapacityError("label_cols", len(self._label_col) + 1)
            self._label_col[key] = col = len(self._label_col)
        return col

    def label_col_lookup(self, key: str) -> int:
        """Column for a key, NONE if no node carries it (the selector then
        matches no node's label — pods repack every cycle, so a key that
        appears later is picked up on the next pack)."""
        return self._label_col.get(key, NONE)

    def pod_label_col(self, key: str) -> int:
        """Register (or fetch) the pod-label column for a key. Registered
        from BOTH pod labels and term selectors so that whichever side packs
        first, the (col, value) match stays consistent."""
        col = self._pod_label_col.get(key)
        if col is None:
            if len(self._pod_label_col) >= self.caps.pod_label_cols:
                raise CapacityError("pod_label_cols",
                                    len(self._pod_label_col) + 1)
            self._pod_label_col[key] = col = len(self._pod_label_col)
        return col

    def topo_col(self, key: str) -> int:
        """Register (or fetch) the topology-key index for a term/constraint
        topology key. A NEW key after nodes were packed backfills the
        topo_dom column for every packed row from the retained node labels."""
        tk = self._topo_col.get(key)
        if tk is not None:
            return tk
        if len(self._topo_col) >= self.caps.topo_cols:
            raise CapacityError("topo_cols", len(self._topo_col) + 1)
        self._topo_col[key] = tk = len(self._topo_col)
        self._tk_key.append(key)
        self._tk_domains.append({})
        if self._row_node_labels:
            off, _ = self.node_codec._i32_off["topo_dom"]
            for row, labels in self._row_node_labels.items():
                value = labels.get(key)
                dom = (self.domain_id(tk, self._i(value))
                       if value is not None else NONE)
                self.node_i32[row, off + tk] = dom
            self._dirty_full["node"] = True
        return tk

    def domain_id(self, tk: int, value_id: int) -> int:
        """Compact per-topology-key domain index for a label value."""
        dmap = self._tk_domains[tk]
        d = dmap.get(value_id)
        if d is None:
            d = dmap[value_id] = len(dmap)
            if d >= self.caps.domain_cap:
                raise CapacityError("domains", d + 1)
        return d

    def ext_col(self, resource_name: str) -> int:
        col = self._ext_index.get(resource_name)
        if col is None:
            nxt = F.NUM_NATIVE_COLS + len(self._ext_index)
            if nxt >= self.caps.res_cols:
                raise CapacityError("ext_resources", len(self._ext_index) + 1)
            self._ext_index[resource_name] = col = nxt
        return col

    def _res_row64(self, r: Resource) -> np.ndarray:
        """Exact float64 column image (exact for byte values < 2^53:
        /MI is a power-of-two scale)."""
        row = np.zeros((self.caps.res_cols,), np.float64)
        row[F.COL_CPU] = r.milli_cpu
        row[F.COL_MEM] = r.memory / MI
        row[F.COL_EPH] = r.ephemeral_storage / MI
        row[F.COL_PODS] = r.allowed_pod_number
        for name, v in r.scalar.items():
            row[self.ext_col(name)] = v
        return row

    def _res_row(self, r: Resource, capacity: bool = False) -> np.ndarray:
        """Pack a Resource into its f32 column image. f32 is EXACT for
        Mi-granular memory up to 16 TiB and integer values up to 2^24
        (ops/features.py unit notes) — but odd-byte memory or huge
        extended-resource counts are not representable, and a silently
        nearest-rounded image could flip the device fit compare against
        the exact-integer semantics of fitsRequest (fit.go:509-592).
        Non-representable quantities are therefore rounded
        CONSERVATIVELY: demand (pod requests) rounds UP;
        ``capacity=True`` (node allocatable, and preemption freed-amount
        rows, which add back onto capacity) rounds DOWN. Differences
        like free = alloc - requested are computed in float64 and
        floored (_free_nzr_of): subtracting two f32 images would round
        to NEAREST and could overstate headroom."""
        return _round_row_f32(self._res_row64(r), up=not capacity)

    def _pairs(self, labels: dict[str, str], cap: int, what: str
               ) -> tuple[np.ndarray, np.ndarray]:
        if len(labels) > cap:
            raise CapacityError(what, len(labels))
        k = np.full((cap,), NONE, np.int32)
        v = np.full((cap,), NONE, np.int32)
        for idx, (key, val) in enumerate(labels.items()):
            k[idx] = self._i(key)
            v[idx] = self._i(val)
        return k, v

    # ------------- node rows -------------

    def row_of(self, name: str) -> int:
        return self._row_of.get(name, -1)

    def name_of_row(self, row: int) -> str | None:
        return self._row_names[row] if 0 <= row < len(self._row_names) else None

    # ------------- preemption dry-run views -------------

    def table_valid_mask(self, exclude_uids) -> np.ndarray:
        """[PT] bool, False at the slots of ``exclude_uids``: the victim
        masking a preemption dry-run feeds to preempt_feasible (the device
        analog of RemovePod in the reference's per-node dry-run,
        preemption.go:682)."""
        m = np.ones((self.caps.pods,), bool)
        for uid in exclude_uids:
            s = self._pod_slot.get(uid)
            if s is not None:
                m[s] = False
        return m

    def free_matrix(self) -> np.ndarray:
        """[N, R] f32 copy of the free-resource columns from the host-side
        node blobs — the base a dry-run adds evicted requests onto."""
        off, size = self.node_codec._f32_off["free"]
        return self.node_f32[:, off:off + size].copy()

    def free_fingerprint(self) -> str:
        """Content hash of the free matrix, memoized per sync: the gang
        capacity memo's freshness token. CONTENT-keyed on purpose — a
        reserve-then-rollback wave bumps the cache version but returns
        free to identical bytes, and a version-keyed token would churn
        the memo forever (the async bound would never land while a
        doomed gang keeps reserving and rolling back)."""
        if self._free_fp is None or self._free_fp[0] != self._last_sync:
            import hashlib

            h = hashlib.blake2b(self.free_matrix().tobytes(),
                                digest_size=8).hexdigest()
            self._free_fp = (self._last_sync, h)
        return self._free_fp[1]

    def _free_nzr_of(self, info: NodeInfo,
                     alloc64: np.ndarray | None = None
                     ) -> tuple[np.ndarray, np.ndarray]:
        # exact float64 difference, floored into f32: alloc_f32 - req_f32
        # would round to NEAREST and can overstate the exact free
        if alloc64 is None:
            alloc64 = self._res_row64(info.allocatable)
        free = _round_row_f32(alloc64 - self._res_row64(info.requested),
                              up=False)
        free[F.COL_PODS] = info.allocatable.allowed_pod_number - len(info.pods)
        nzr = np.asarray(
            [info.non_zero_requested.milli_cpu,
             info.non_zero_requested.memory / MI], np.float32)
        return free, nzr

    def _pack_ports(self, info: NodeInfo, f: dict[str, np.ndarray],
                    row: int | None = None) -> None:
        caps = self.caps
        entries = [(ip, proto, port)
                   for ip, s in info.used_ports.ports.items()
                   for (proto, port) in s]
        if len(entries) > caps.node_ports:
            raise CapacityError("node_ports", len(entries))
        if row is not None:
            (self._rows_with_ports.add(row) if entries
             else self._rows_with_ports.discard(row))
        pi = np.full((caps.node_ports,), NONE, np.int32)
        pp = np.full((caps.node_ports,), NONE, np.int32)
        pn = np.full((caps.node_ports,), NONE, np.int32)
        for i, (ip, proto, port) in enumerate(entries):
            pi[i] = self._i(ip)
            pp[i] = self._i(proto)
            pn[i] = port
        f["port_ips"], f["port_protos"], f["port_nums"] = pi, pp, pn

    def _update_node_row_resources(self, row: int, info: NodeInfo) -> None:
        """Fast repack for pod-only changes (the node object itself is
        unchanged): only free/nonzeroRequested/ports columns move, plus the
        pod-table reconcile — the common per-cycle case, ~10x cheaper than a
        full row repack."""
        f: dict[str, np.ndarray] = {}
        f["free"], f["nonzero_requested"] = self._free_nzr_of(info)
        self._pack_ports(info, f, row)
        nc = self.node_codec
        for name, arr in f.items():
            kind_off = nc._f32_off.get(name)
            if kind_off is not None:
                off, size = kind_off
                self.node_f32[row, off:off + size] = arr
            else:
                off, size = nc._i32_off[name]
                self.node_i32[row, off:off + size] = arr
        self._dirty_rows.add(row)
        self._reconcile_node_pods(row, info)

    def _pack_node_row(self, row: int, info: NodeInfo) -> None:
        caps = self.caps
        node = info.node
        assert node is not None
        f: dict[str, np.ndarray] = {}
        alloc64 = self._res_row64(info.allocatable)
        f["allocatable"] = _round_row_f32(alloc64, up=False)
        f["free"], f["nonzero_requested"] = self._free_nzr_of(info, alloc64)
        f["nominated_req"] = self._nominated_req_of_row.get(
            row, np.zeros((caps.res_cols,), np.float32))
        f["node_valid"] = np.bool_(True)
        f["unschedulable"] = np.bool_(node.spec.unschedulable)
        f["node_name_id"] = np.int32(self._i(node.metadata.name))
        vals = np.full((caps.label_cols,), NONE, np.int32)
        nums = np.full((caps.label_cols,), np.nan, np.float32)
        for key, value in node.metadata.labels.items():
            col = self.label_col(key)
            vid = self._i(value)
            vals[col] = vid
            nums[col] = self.interner.numeric(vid)
        f["label_col_vals"] = vals
        f["label_col_nums"] = nums
        doms = np.full((caps.topo_cols,), NONE, np.int32)
        for tk, key in enumerate(self._tk_key):
            value = node.metadata.labels.get(key)
            if value is not None:
                doms[tk] = self.domain_id(tk, self._i(value))
        f["topo_dom"] = doms
        self._row_node_labels[row] = node.metadata.labels
        self._dirty_rows.add(row)
        if len(node.spec.taints) > caps.node_taints:
            raise CapacityError("node_taints", len(node.spec.taints))
        tk = np.full((caps.node_taints,), NONE, np.int32)
        tv = np.full((caps.node_taints,), NONE, np.int32)
        te = np.full((caps.node_taints,), NONE, np.int32)
        for i, t in enumerate(node.spec.taints):
            tk[i] = self._i(t.key)
            tv[i] = self._i(t.value)
            te[i] = F.effect_id(t.effect)
        f["taint_keys"], f["taint_vals"], f["taint_effects"] = tk, tv, te
        (self._rows_with_taints.add(row) if node.spec.taints
         else self._rows_with_taints.discard(row))
        self._pack_ports(info, f, row)
        imgs = list(info.image_sizes.items())
        (self._rows_with_images.add(row) if imgs
         else self._rows_with_images.discard(row))
        if len(imgs) > caps.node_images:
            imgs = imgs[: caps.node_images]  # best-effort: scoring-only signal
        ii = np.full((caps.node_images,), NONE, np.int32)
        isz = np.zeros((caps.node_images,), np.float32)
        for i, (name, size) in enumerate(imgs):
            ii[i] = self._i(name)
            isz[i] = size / MI
        f["image_ids"], f["image_sizes"] = ii, isz
        self.node_codec.pack_into(self.node_f32[row], self.node_i32[row], f)
        self._row_node_obj[row] = node
        self._reconcile_node_pods(row, info)

    def _reconcile_node_pods(self, row: int, info: NodeInfo) -> None:
        name = info.name
        current = self._node_pods.setdefault(name, {})
        live_uids = {p.pod.metadata.uid for p in info.pods}
        for uid in list(current):
            # nominated slots are owned by set_nominated, not the node diff
            if uid not in live_uids and not uid.startswith("nominated:"):
                self._release_pod_slot(uid)
        for pi in info.pods:
            uid = pi.pod.metadata.uid
            if (uid not in current
                    or self._pod_obj.get(uid) is not pi.pod):
                # new on this node, moved here, or the pod object was replaced
                # (update): repack. Releasing first also covers the
                # moved-before-source-reconciled ordering.
                self._release_pod_slot(uid)
                self._pack_pod_slot(uid, pi, row, name)

    def pod_labels_row(self, labels: dict[str, str]) -> np.ndarray:
        """Labels as a pod-label-column value row [Kp] (registers keys)."""
        row = np.full((self.caps.pod_label_cols,), NONE, np.int32)
        for k, v in labels.items():
            row[self.pod_label_col(k)] = self._i(v)
        return row

    def _pack_term_group(self, pi_terms, weights, pod: Pod, prefix: str,
                         f: dict[str, np.ndarray]) -> None:
        """One (anti)affinity term group -> tk/ns/ns_all/sel_cols/sel_ops/
        sel_vals arrays (+ weight for preferred groups)."""
        caps = self.caps
        A, NS, MS, V2 = (caps.aff_terms, caps.aff_ns, caps.aff_sel,
                         caps.aff_sel_vals)
        tk = np.full((A,), NONE, np.int32)
        ns = np.full((A, NS), NONE, np.int32)
        nall = np.zeros((A,), bool)
        sc = np.full((A, MS), NONE, np.int32)
        so = np.full((A, MS), NONE, np.int32)
        sv = np.full((A, MS, V2), NONE, np.int32)
        if len(pi_terms) > A:
            raise CapacityError("aff_terms", len(pi_terms))
        for t_idx, term in enumerate(pi_terms):
            self._pack_aff_term(term, pod, tk, ns, nall, sc, so, sv, t_idx)
        f[f"{prefix}_tk"] = tk
        f[f"{prefix}_ns"] = ns
        f[f"{prefix}_ns_all"] = nall
        f[f"{prefix}_sel_cols"] = sc
        f[f"{prefix}_sel_ops"] = so
        f[f"{prefix}_sel_vals"] = sv
        if weights is not None:
            w = np.zeros((A,), np.int32)
            w[: len(weights)] = weights
            f[f"{prefix}_weight"] = w

    def _table_template(self) -> np.ndarray:
        """Packed pods_i32 row of a term-free table pod (pod_valid=True,
        everything else at defaults): the fast-path base every no-affinity
        bound pod copies instead of re-deriving ~30 padded term arrays
        (the dominant host cost of committing constraint-free workloads)."""
        if self._table_i32_tmpl is None:
            tf32, ti32 = self.table_codec.alloc(1)
            pi = PodInfo(Pod())
            f: dict[str, np.ndarray] = {}
            f["pod_valid"] = np.bool_(True)
            f["pod_node"] = np.int32(0)
            f["pod_ns"] = np.int32(NONE)
            f["pod_uid"] = np.int32(NONE)
            f["pod_nominated"] = np.bool_(False)
            f["pt_label_vals"] = np.full((self.caps.pod_label_cols,), NONE,
                                         np.int32)
            self._pack_term_group([], None, pi.pod, "pod_anti", f)
            self._pack_term_group([], None, pi.pod, "pod_aff", f)
            self._pack_term_group([], [], pi.pod, "pod_paff", f)
            self._pack_term_group([], [], pi.pod, "pod_panti", f)
            self.table_codec.pack_into(tf32[0], ti32[0], f)
            self._table_i32_tmpl = ti32[0]
        return self._table_i32_tmpl

    def _pack_pod_slot(self, uid: str, pi: PodInfo, row: int, node_name: str,
                       nominated: bool = False) -> None:
        self._note_namespace(pi.pod.metadata.namespace)
        if not self._free_slots:
            raise CapacityError("pods", self.caps.pods + 1)
        slot = self._free_slots.pop()
        pod = pi.pod
        has_terms = bool(pi.required_anti_affinity_terms
                         or pi.required_affinity_terms
                         or pi.preferred_affinity_terms
                         or pi.preferred_anti_affinity_terms)
        if not has_terms:
            # template fast path: copy + patch the 5 scalar fields + labels
            dst = self.pods_i32[slot]
            dst[:] = self._table_template()
            tc = self.table_codec
            dst[tc._i32_off["pod_node"][0]] = row
            dst[tc._i32_off["pod_ns"][0]] = self._i(pod.metadata.namespace)
            dst[tc._i32_off["pod_uid"][0]] = self._i(pod.metadata.uid)
            dst[tc._i32_off["pod_nominated"][0]] = 1 if nominated else 0
            if pod.metadata.labels:
                off, size = tc._i32_off["pt_label_vals"]
                dst[off:off + size] = self.pod_labels_row(pod.metadata.labels)
            self._dirty_slots.add(slot)
            self._pod_slot[uid] = slot
            self._node_pods[node_name][uid] = slot
            self._pod_obj[uid] = pod
            self._node_of_pod[uid] = node_name
            return
        f: dict[str, np.ndarray] = {}
        f["pod_valid"] = np.bool_(True)
        f["pod_node"] = np.int32(row)
        f["pod_ns"] = np.int32(self._i(pod.metadata.namespace))
        f["pod_uid"] = np.int32(self._i(pod.metadata.uid))
        f["pod_nominated"] = np.bool_(nominated)
        f["pt_label_vals"] = self.pod_labels_row(pod.metadata.labels)
        self._pack_term_group(pi.required_anti_affinity_terms, None, pod,
                              "pod_anti", f)
        self._pack_term_group(pi.required_affinity_terms, None, pod,
                              "pod_aff", f)
        self._pack_term_group(
            [w.pod_affinity_term for w in pi.preferred_affinity_terms],
            [w.weight for w in pi.preferred_affinity_terms], pod, "pod_paff", f)
        self._pack_term_group(
            [w.pod_affinity_term for w in pi.preferred_anti_affinity_terms],
            [w.weight for w in pi.preferred_anti_affinity_terms], pod,
            "pod_panti", f)
        empty_f32 = self.pods_i32[slot, :0].view(np.float32)
        self.table_codec.pack_into(empty_f32, self.pods_i32[slot], f)
        self._dirty_slots.add(slot)
        self._pod_slot[uid] = slot
        self._node_pods[node_name][uid] = slot
        self._pod_obj[uid] = pod
        self._node_of_pod[uid] = node_name
        all_terms = (pi.required_anti_affinity_terms
                     + pi.required_affinity_terms
                     + [w.pod_affinity_term for w in pi.preferred_affinity_terms]
                     + [w.pod_affinity_term
                        for w in pi.preferred_anti_affinity_terms])
        if all_terms:
            self._uids_with_terms.add(uid)
        if any(t.namespace_selector is not None
               and (t.namespace_selector.match_labels
                    or t.namespace_selector.match_expressions)
               for t in all_terms):
            self._uids_with_nssel.add(uid)

    @staticmethod
    def _effective_exprs(sel, owner_labels: dict[str, str],
                         match_label_keys, mismatch_label_keys):
        """A LabelSelector as (key, operator, values) requirement tuples,
        with match/mismatchLabelKeys merged as In/NotIn requirements copying
        the owner pod's values (strategy.go
        applyMatchLabelKeysAndMismatchLabelKeys: keys absent from the owner's
        labels are skipped; nil selector skips the merge and matches nothing).
        Returns None for a nil selector."""
        if sel is None:
            return None
        exprs = selector_requirements(sel)
        for k in match_label_keys:
            if k in owner_labels:
                exprs.append((k, "In", [owner_labels[k]]))
        for k in mismatch_label_keys:
            if k in owner_labels:
                exprs.append((k, "NotIn", [owner_labels[k]]))
        return exprs

    def _pack_exprs(self, exprs, sel_c: np.ndarray, sel_o: np.ndarray,
                    sel_v: np.ndarray, t_idx: int) -> None:
        """Requirement tuples -> op-coded expression rows at term t_idx.
        exprs=None (nil selector, labels.Nothing()) packs a sentinel In
        expression no real value can satisfy."""
        caps = self.caps
        if exprs is None:
            sel_c[t_idx, 0] = 0
            sel_o[t_idx, 0] = F.op_id("In")
            sel_v[t_idx, 0, 0] = F.IMPOSSIBLE
            return
        if len(exprs) > caps.aff_sel:
            raise CapacityError("aff_sel", len(exprs))
        for i, (k, op, values) in enumerate(exprs):
            sel_c[t_idx, i] = self.pod_label_col(k)
            sel_o[t_idx, i] = F.op_id(op)
            if len(values) > caps.aff_sel_vals:
                raise CapacityError("aff_sel_vals", len(values))
            for j, v in enumerate(values):
                sel_v[t_idx, i, j] = self._i(v)

    def _note_namespace(self, ns_name: str) -> None:
        """Record a pod's namespace. A namespace first seen AFTER table pods
        with namespaceSelector terms were packed invalidates their unrolled
        lists (a DoesNotExist/NotIn selector can match the new namespace's
        empty/absent labels) — repack them."""
        if ns_name in self._known_pod_ns:
            return
        self._known_pod_ns.add(ns_name)
        if self._uids_with_nssel:
            self._repack_nssel_pods()

    def _repack_nssel_pods(self) -> None:
        for uid in list(self._uids_with_nssel):
            node_name = self._node_of_pod.get(uid)
            pod = self._pod_obj.get(uid)
            row = self._row_of.get(node_name or "")
            if node_name is None or pod is None or row is None:
                continue
            self._release_pod_slot(uid)
            # a "nominated:<uid>" overlay slot must keep its pod_nominated
            # flag through the repack, or the dual-pass rule
            # (RunFilterPluginsWithNominatedPods) breaks for it
            self._pack_pod_slot(uid, PodInfo(pod), row, node_name,
                                nominated=uid in self._nominated_uids)

    def _resolve_term_namespaces(self, term: PodAffinityTerm, owner: Pod
                                 ) -> tuple[list[str], bool]:
        """(explicit namespace list, all-namespaces flag) for a term.

        The pack-time analog of the reference's
        mergeAffinityTermNamespacesIfNotEmpty (interpodaffinity/plugin.go:123):
        a non-empty namespaceSelector unrolls into explicit names over the
        namespace store PLUS every namespace a packed pod lives in (labels
        default to {} when no Namespace object exists — the reference's nil
        nsLabels, so DoesNotExist/NotIn selectors match them). If the
        selector matches every known namespace, the all-namespaces flag is
        packed instead of the list — exact under the repack-on-new-namespace
        rule (_note_namespace) and immune to aff_ns capacity blowup for
        broad selectors. The EMPTY selector ({}) always matches everything;
        nil selector + no explicit namespaces defaults to the owner's
        namespace (getNamespacesFromPodAffinityTerm, types.go:749)."""
        explicit = list(term.namespaces)
        nssel = term.namespace_selector
        if nssel is not None:
            if not nssel.match_labels and not nssel.match_expressions:
                return sorted(set(explicit)), True
            universe = set(self._namespaces) | self._known_pod_ns
            matched = [name for name in universe
                       if label_selector_matches(
                           nssel, self._namespaces.get(name, {}))]
            if universe and len(matched) == len(universe):
                return sorted(set(explicit)), True
            explicit.extend(matched)
        elif not explicit:
            explicit = [owner.metadata.namespace]
        return sorted(set(explicit)), False

    def _pack_aff_term(self, term: PodAffinityTerm, pod: Pod,
                       tk: np.ndarray, ns: np.ndarray, ns_all: np.ndarray,
                       sel_c: np.ndarray, sel_o: np.ndarray,
                       sel_v: np.ndarray, t_idx: int) -> None:
        """Shared (anti)affinity term encoding: topology key -> tk index,
        namespaces resolved/unrolled, selector -> op-coded expressions."""
        caps = self.caps
        tk[t_idx] = self.topo_col(term.topology_key)
        self._used_tks.add(int(tk[t_idx]))
        namespaces, all_flag = self._resolve_term_namespaces(term, pod)
        if len(namespaces) > caps.aff_ns:
            raise CapacityError("aff_ns", len(namespaces))
        for i, n in enumerate(namespaces):
            ns[t_idx, i] = self._i(n)
        ns_all[t_idx] = all_flag
        exprs = self._effective_exprs(term.label_selector, pod.metadata.labels,
                                      term.match_label_keys,
                                      term.mismatch_label_keys)
        self._pack_exprs(exprs, sel_c, sel_o, sel_v, t_idx)

    def term_matches_pod(self, term: PodAffinityTerm, owner: Pod,
                         target: Pod) -> bool:
        """Host oracle: does `term` (owned by `owner`) select `target`?
        (AffinityTerm.Matches, framework/types.go:545) — full LabelSelector
        + namespaceSelector + match/mismatchLabelKeys semantics."""
        namespaces, ns_all = self._resolve_term_namespaces(term, owner)
        if not ns_all and target.metadata.namespace not in namespaces:
            return False
        exprs = self._effective_exprs(term.label_selector,
                                      owner.metadata.labels,
                                      term.match_label_keys,
                                      term.mismatch_label_keys)
        return requirements_match(exprs, target.metadata.labels)

    def _release_pod_slot(self, uid: str) -> None:
        slot = self._pod_slot.pop(uid, None)
        if slot is None:
            return
        self.pods_i32[slot] = 0  # pod_valid -> False, rest zeroed
        self._free_slots.append(slot)
        self._dirty_slots.add(slot)
        self._pod_obj.pop(uid, None)
        self._uids_with_terms.discard(uid)
        self._uids_with_nssel.discard(uid)
        node = self._node_of_pod.pop(uid, None)
        if node is not None:
            self._node_pods.get(node, {}).pop(uid, None)

    def _invalidate_row(self, name: str) -> None:
        row = self._row_of.pop(name)
        self._row_gen.pop(name, None)
        self._row_names[row] = None
        self.node_f32[row] = 0.0
        self.node_i32[row] = 0  # node_valid -> False
        self._dirty_rows.add(row)
        self._row_node_labels.pop(row, None)
        self._row_node_obj.pop(row, None)
        self._nominated_req_of_row.pop(row, None)
        self._rows_with_taints.discard(row)
        self._rows_with_ports.discard(row)
        self._rows_with_images.discard(row)
        for uid in list(self._node_pods.get(name, {})):
            self._release_pod_slot(uid)
        self._node_pods.pop(name, None)
        self._free_rows.append(row)

    def patch_node(self, name: str, info: NodeInfo | None
                   ) -> tuple[int, np.ndarray, np.ndarray] | None:
        """Repack ONE node's row from its LIVE cache aggregate, outside the
        snapshot sync — the host half of chain-surviving churn. The mirror
        row moves exactly as a full sync would have moved it (same pack
        helpers, pod-table reconcile included) and ``_row_gen`` records the
        live generation so a later full sync skips the already-consistent
        row. Returns ``(row, free, nzr)`` for the caller to scatter into
        the device-resident chain (zeros for a removed node — a zeroed
        free row fits nothing, matching node_valid=False), or None when
        the node was never mirrored (nothing to patch). Raises
        CapacityError when the node table is full or the node outgrows a
        pack capacity — the caller falls back to whole-chain invalidation
        and the normal resync/_grow ladder."""
        row = self._row_of.get(name)
        if info is None or info.node is None:
            if row is None:
                return None
            self._invalidate_row(name)
            self._free_fp = None
            return (row, np.zeros((self.caps.res_cols,), np.float32),
                    np.zeros((2,), np.float32))
        if row is None:
            if not self._free_rows:
                raise CapacityError("nodes", len(self._row_of) + 1)
            row = self._free_rows.pop()
            self._row_of[name] = row
            self._row_names[row] = name
            self._pack_node_row(row, info)
        elif self._row_node_obj.get(row) is info.node:
            self._update_node_row_resources(row, info)
        else:
            self._pack_node_row(row, info)
        self._row_gen[name] = info.generation
        self._free_fp = None
        return (row, *self._free_nzr_of(info))

    # ------------- sync -------------

    def sync(self, snapshot: Snapshot) -> int:
        """Incrementally repack rows for nodes whose generation advanced.
        Returns the number of rows repacked."""
        # O(1) no-op when the snapshot hasn't changed since the last sync of
        # this same snapshot object (Snapshot.version is bumped by every
        # mutating Cache.update_snapshot)
        if self._last_sync == (id(snapshot), snapshot.version):
            return 0
        self._last_sync = (id(snapshot), snapshot.version)
        # namespace set changed: refresh the store and repack every table pod
        # whose terms carry a namespaceSelector (their unrolled ns lists are
        # stale) — the incremental analog of the reference resolving
        # namespaceSelectors freshly each cycle
        if snapshot.ns_generation != self._ns_gen:
            self._ns_gen = snapshot.ns_generation
            self._namespaces = snapshot.namespaces
            self._repack_nssel_pods()
        live = {info.name for info in snapshot.node_info_list}
        repacked = 0
        # removals first so a same-sync node swap can reuse the freed row
        for name in list(self._row_of):
            if name not in live:
                self._invalidate_row(name)
                repacked += 1
        for info in snapshot.node_info_list:
            name = info.name
            row = self._row_of.get(name)
            if row is None:
                if not self._free_rows:
                    raise CapacityError("nodes", len(self._row_of) + 1)
                row = self._free_rows.pop()
                self._row_of[name] = row
                self._row_names[row] = name
            if self._row_gen.get(name) != info.generation:
                if self._row_node_obj.get(row) is info.node:
                    # pod-only change: resources/ports fast path
                    self._update_node_row_resources(row, info)
                else:
                    self._pack_node_row(row, info)
                self._row_gen[name] = info.generation
                repacked += 1
        return repacked

    def _push(self, key: str, host_buf: np.ndarray, dirty: set[int],
              full: bool) -> None:
        """Refresh one device buffer: full upload on first use / bulk change,
        otherwise a row-scatter of only the dirty rows into the resident
        (donated) HBM buffer — the device half of the incremental
        UpdateSnapshot (a few hundred KB per cycle instead of the whole
        multi-MB mirror over the host<->TPU link)."""
        dev = self._dev.get(key)
        if dev is None or full or len(dirty) > max(64, host_buf.shape[0] // 4):
            sh = self._dev_sharding.get(key)
            self._dev[key] = (jnp.asarray(host_buf) if sh is None
                              else jax.device_put(host_buf, sh))
            return
        if not dirty:
            return
        idx = sorted(dirty)
        k = 1
        while k < len(idx):
            k *= 2
        # pad with duplicates of the last row: same index + same data is an
        # idempotent write, and keeps the scatter shape in pow2 buckets so
        # XLA compiles one kernel per bucket, not per row-count
        idx = idx + [idx[-1]] * (k - len(idx))
        arr = np.asarray(idx, np.int32)
        scatter = self._scatter_fns.get(key, _scatter_rows_jit)
        self._dev[key] = scatter(dev, jnp.asarray(arr),
                                 jnp.asarray(host_buf[arr]))

    def to_blobs(self) -> ClusterBlobs:
        """Refresh the device-resident mirror (incremental row scatter or
        full upload) and return the ClusterBlobs handles."""
        full_node = self._dirty_full["node"]
        self._push("node_f32", self.node_f32, self._dirty_rows, full_node)
        self._push("node_i32", self.node_i32, self._dirty_rows, full_node)
        self._push("pods_i32", self.pods_i32, self._dirty_slots,
                   self._dirty_full["pods"])
        self._dirty_full = {"node": False, "pods": False}
        self._dirty_rows.clear()
        self._dirty_slots.clear()
        return ClusterBlobs(node_f32=self._dev["node_f32"],
                            node_i32=self._dev["node_i32"],
                            pods_i32=self._dev["pods_i32"])

    def to_device(self) -> ClusterTensors:
        """ClusterTensors view (single jitted unpack dispatch) — test/tooling
        convenience; the scheduling pipeline unpacks blobs inside its own jit."""
        return _unpack_cluster_jit(self.to_blobs(), self.caps)

    def _hysteresis(self, hw_attr: str, low_attr: str, need: int) -> int:
        """Sticky pow2 bucket: expand to ``need`` immediately; shrink by
        ONE halving only after BUCKET_DECAY_LAUNCHES consecutive launches
        whose demand fit in half the bucket. The compile-count analog of
        TCP slow decrease — an oscillating demand signal settles on the
        high-water program instead of recompiling every swing."""
        hw = getattr(self, hw_attr)
        if need >= hw:
            setattr(self, hw_attr, need)
            setattr(self, low_attr, 0)
            return need
        if need <= hw // 2:
            low = getattr(self, low_attr) + 1
            if low >= BUCKET_DECAY_LAUNCHES:
                hw = max(need, hw // 2)
                setattr(self, hw_attr, hw)
                setattr(self, low_attr, 0)
            else:
                setattr(self, low_attr, low)
        else:
            setattr(self, low_attr, 0)
        return hw

    def adopt_hysteresis(self, prev: "Mirror") -> None:
        """Carry the sticky domain-bucket high-water mark across a
        capacity re-bucket (scheduler._grow builds a FRESH mirror):
        without this a rebuilt mirror re-derives a smaller bucket from
        its still-empty domain tables and the next churn swing pays the
        compile again."""
        self._d_hw = prev._d_hw

    def launch_d_cap(self, enable_topology: bool) -> int:
        """The static d_cap for one launch: the domain bucket when the
        launch runs topology kernels, else a CANONICAL 0 — a no-topology
        program never reads domains, and keying it on the domain count
        would make a scaled-down warmup (fewer nodes -> smaller bucket)
        compile a DIFFERENT program than the full-scale run, paying a
        fresh multi-second XLA compile on the first measured batch."""
        if not enable_topology:
            return 0
        return min(self._hysteresis("_d_hw", "_d_low",
                                    self.domain_bucket()),
                   self.caps.domain_cap)

    def domain_bucket(self) -> int:
        """Static scatter-space size for the next launch: power-of-two over
        the max domain count among topology keys any packed term/constraint
        references (>= 8 to limit recompiles). The device analog of sizing
        the reference's topologyPair hash maps to what the workload touches."""
        need = max((len(self._tk_domains[tk]) for tk in self._used_tks),
                   default=1)
        d = 8
        while d < need:
            d *= 2
        return min(d, self.caps.domain_cap)

    def gang_pack_domain(self) -> tuple[int, int]:
        """(tk, d_bucket) for the gang packer's topology-close fill
        order: the ZONE topology key's column and a pow2 domain bucket
        (+1 slot for the pseudo-domain of unlabeled nodes) when any
        node carries a zone label; (-1, 8) otherwise — the packer then
        fills capacity-greedy with every node in one shared domain."""
        from kubernetes_tpu.api.objects import LABEL_ZONE

        tk = self._topo_col.get(LABEL_ZONE)
        if tk is None or not self._tk_domains[tk]:
            return -1, 8
        need = len(self._tk_domains[tk]) + 1
        d = 8
        while d < need:
            d *= 2
        return tk, min(d, self.caps.domain_cap + 1)

    @staticmethod
    def batch_topology_soft_only(pods: list[Pod]) -> bool:
        """True when no batch pod carries topology work that CONSTRAINS:
        required (anti)affinity terms or DoNotSchedule spread. A soft-only
        batch's topology terms are pure Score work, which the parallel
        auction can fuse (preferred weights + ScheduleAnyway spread) — the
        preferred-band workloads stop paying the serial commit scan."""
        for p in pods:
            a = p.spec.affinity
            if a is not None:
                pa, pan = a.pod_affinity, a.pod_anti_affinity
                if pa is not None and pa.required:
                    return False
                if pan is not None and pan.required:
                    return False
            for t in p.spec.topology_spread_constraints:
                if t.when_unsatisfiable == "DoNotSchedule":
                    return False
        return True

    @staticmethod
    def batch_has_topology(pods: list[Pod]) -> bool:
        """Host-side PreFilter-Skip: does any pod in the batch carry
        (anti)affinity terms or topology spread constraints?"""
        for p in pods:
            a = p.spec.affinity
            if a is not None and (a.pod_affinity is not None
                                  or a.pod_anti_affinity is not None):
                return True
            if p.spec.topology_spread_constraints:
                return True
        return False

    def table_has_topology(self) -> bool:
        """True if any scheduled pod in the table carries (anti)affinity
        terms — those reject (existing anti-affinity) or score (existing
        required/preferred terms) even a constraint-free incoming batch."""
        return bool(self._uids_with_terms)

    def set_nominated(self, by_node: dict[str, list[Pod]]) -> None:
        """Refresh the nominated-pod overlay: pending preemptors with a
        NominatedNodeName occupy pod-table slots on their nominated row
        (anti-affinity counts them; required-affinity presence and scoring
        exclude them via pod_nominated — the device analog of the dual pass
        in RunFilterPluginsWithNominatedPods, runtime/framework.go:989) and
        reserve their resource requests in the node row's nominated_req."""
        for uid in list(self._nominated_uids):
            self._release_pod_slot(uid)
        self._nominated_uids.clear()
        off, size = self.node_codec._f32_off["nominated_req"]
        for row in list(self._nominated_req_of_row):
            self.node_f32[row, off:off + size] = 0.0
            self._dirty_rows.add(row)
        self._nominated_req_of_row.clear()
        for node_name, pods in by_node.items():
            row = self._row_of.get(node_name)
            if row is None or not pods:
                continue
            req_sum = np.zeros((self.caps.res_cols,), np.float32)
            for pod in pods:
                pi = PodInfo(pod)
                key = "nominated:" + pod.metadata.uid
                self._pack_pod_slot(key, pi, row, node_name, nominated=True)
                self._nominated_uids.add(key)
                req_sum += self._res_row(pi.request)
                req_sum[F.COL_PODS] += 1.0
            self._nominated_req_of_row[row] = req_sum
            self.node_f32[row, off:off + size] = req_sum
            self._dirty_rows.add(row)

    # ------------- pod packing -------------

    def pack_pod(self, pod: Pod, active_only: bool = False
                 ) -> dict[str, np.ndarray]:
        """Pod -> PodFeatures field dict (numpy).

        With ``active_only`` the dict contains ONLY the fields this pod
        actually uses; absent fields take their defaults from the packed
        empty-pod template (_pod_template) — the fast path that keeps
        per-pod pack cost proportional to the pod's features, not the
        schema size."""
        caps = self.caps
        pi = PodInfo(pod)
        out: dict[str, np.ndarray] = {}
        out["req"] = self._res_row(pi.request)
        out["req"][F.COL_PODS] = 1.0  # each pod consumes one pod slot
        out["nonzero_req"] = np.asarray(
            [pi.non_zero_request.milli_cpu, pi.non_zero_request.memory / MI],
            np.float32)
        out["num_containers"] = np.float32(
            len(pod.spec.containers) + len(pod.spec.init_containers))
        out["priority"] = np.int32(pod.priority())
        out["ns"] = np.int32(self._i(pod.metadata.namespace))
        out["name_id"] = np.int32(self._i(pod.metadata.name))
        out["uid_id"] = np.int32(self._i(pod.metadata.uid))
        # own-reservation add-back is only sound if this pod's reservation is
        # actually inside nominated_req (set_nominated ran with it); a stale
        # status.nominatedNodeName must NOT inflate free
        nom = pod.status.nominated_node_name
        reserved = ("nominated:" + pod.metadata.uid) in self._nominated_uids
        out["nominated_row"] = np.int32(
            self._row_of.get(nom, NONE) if nom and reserved else NONE)
        if pod.metadata.labels or not active_only:
            out["plabel_vals"] = self.pod_labels_row(pod.metadata.labels)
        if pod.spec.node_selector or not active_only:
            if len(pod.spec.node_selector) > caps.pod_labels:
                raise CapacityError("pod_labels", len(pod.spec.node_selector))
            ns_cols = np.full((caps.pod_labels,), NONE, np.int32)
            ns_vals = np.full((caps.pod_labels,), NONE, np.int32)
            for idx, (k, v) in enumerate(pod.spec.node_selector.items()):
                ns_cols[idx] = self.label_col_lookup(k)
                ns_vals[idx] = self._i(v)
            out["nodesel_cols"], out["nodesel_vals"] = ns_cols, ns_vals
        aff = pod.spec.affinity
        if (aff is not None and aff.node_affinity is not None) \
                or not active_only:
            pin = self._node_affinity_pin(
                aff.node_affinity if aff is not None else None)
            if pin is not None and active_only:
                # daemonset shape: the whole required clause is one
                # metadata.name In [v] matchFields term — pack the pin id
                # only; the selector/preferred arrays keep their template
                # defaults (and a pin-only batch never transfers them)
                out["aff_pin"] = np.int32(self._i(pin))
            else:
                self._pack_node_affinity(pod, out)
        if pod.spec.tolerations or not active_only:
            self._pack_tolerations(pod, out)
        if any(p.host_port > 0 for c in pod.spec.containers
               for p in c.ports) or not active_only:
            self._pack_host_ports(pod, out)
        if (aff is not None and (aff.pod_affinity is not None
                                 or aff.pod_anti_affinity is not None)) \
                or not active_only:
            self._pack_pod_affinity(pod, pi, out)
        if pod.spec.topology_spread_constraints or not active_only:
            self._pack_spread(pod, out)
        imgs = [c.image for c in pod.spec.containers if c.image]
        if imgs or not active_only:
            out["image_ids"] = np.full((caps.pod_images,), NONE, np.int32)
            for idx, img in enumerate(imgs[: caps.pod_images]):
                out["image_ids"][idx] = self._i(img)
        if pod.spec.node_name or not active_only:
            out["node_name_id"] = np.int32(
                self._i(pod.spec.node_name) if pod.spec.node_name else NONE)
        out["valid"] = np.bool_(True)
        return out

    def _pod_template(self) -> tuple[np.ndarray, np.ndarray]:
        """Packed blob rows of an empty pod: the defaults every active_only
        pack starts from."""
        if self._pod_tmpl is None:
            f32, i32 = self.pod_codec.alloc()
            self.pod_codec.pack_into(f32, i32, self.pack_pod(Pod()))
            self._pod_tmpl = (f32, i32)
        return self._pod_tmpl

    @staticmethod
    def _node_affinity_pin(na) -> str | None:
        """The daemonset-controller pattern: required node affinity whose
        ENTIRE clause is one term holding exactly one matchFields
        metadata.name In [single value] expression, with no preferred
        terms riding along. Returns the pinned node name (semantically a
        NodeName pin under the NodeAffinity plugin), else None."""
        if na is None or na.preferred or na.required is None:
            return None
        terms = na.required.node_selector_terms
        if len(terms) != 1:
            return None
        t = terms[0]
        if t.match_expressions or len(t.match_fields) != 1:
            return None
        f = t.match_fields[0]
        if f.key != "metadata.name" or f.operator != "In" \
                or len(f.values) != 1:
            return None
        return f.values[0]

    def _pack_node_affinity(self, pod: Pod, out: dict[str, np.ndarray]) -> None:
        caps = self.caps
        T, E, V = caps.sel_terms, caps.sel_exprs, caps.sel_vals
        out["aff_pin"] = np.int32(NONE)
        out["sel_term_valid"] = np.zeros((T,), bool)
        out["sel_col"] = np.full((T, E), NONE, np.int32)
        out["sel_op"] = np.full((T, E), NONE, np.int32)
        out["sel_is_field"] = np.zeros((T, E), bool)
        out["sel_vals"] = np.full((T, E, V), NONE, np.int32)
        out["sel_num"] = np.full((T, E), np.nan, np.float32)
        aff = pod.spec.affinity
        required = (aff.node_affinity.required
                    if aff and aff.node_affinity else None)
        if required is not None:
            terms = required.node_selector_terms
            if len(terms) > T:
                raise CapacityError("sel_terms", len(terms))
            for ti, term in enumerate(terms):
                out["sel_term_valid"][ti] = True
                self._pack_term_exprs(term, out["sel_col"], out["sel_op"],
                                      out["sel_is_field"], out["sel_vals"],
                                      out["sel_num"], ti)
        # preferred
        PW = caps.pref_terms
        out["pref_weight"] = np.zeros((PW,), np.int32)
        out["pref_col"] = np.full((PW, E), NONE, np.int32)
        out["pref_op"] = np.full((PW, E), NONE, np.int32)
        out["pref_is_field"] = np.zeros((PW, E), bool)
        out["pref_vals"] = np.full((PW, E, V), NONE, np.int32)
        out["pref_num"] = np.full((PW, E), np.nan, np.float32)
        preferred = (aff.node_affinity.preferred
                     if aff and aff.node_affinity else [])
        if len(preferred) > PW:
            raise CapacityError("pref_terms", len(preferred))
        for ti, wterm in enumerate(preferred):
            out["pref_weight"][ti] = wterm.weight
            self._pack_term_exprs(wterm.preference, out["pref_col"],
                                  out["pref_op"], out["pref_is_field"],
                                  out["pref_vals"], out["pref_num"], ti)

    def _pack_term_exprs(self, term, keys, ops, is_field, vals, nums, ti) -> None:
        caps = self.caps
        exprs = ([(e, False) for e in term.match_expressions]
                 + [(e, True) for e in term.match_fields])
        if len(exprs) > caps.sel_exprs:
            raise CapacityError("sel_exprs", len(exprs))
        for ei, (e, fld) in enumerate(exprs):
            # matchExpressions reference a label COLUMN (NONE if no node
            # carries the key); matchFields (metadata.name) keep col NONE
            keys[ti, ei] = NONE if fld else self.label_col_lookup(e.key)
            ops[ti, ei] = F.op_id(e.operator)
            is_field[ti, ei] = fld
            if len(e.values) > caps.sel_vals:
                raise CapacityError("sel_vals", len(e.values))
            for vi, v in enumerate(e.values):
                vals[ti, ei, vi] = self._i(v)
            if e.operator in ("Gt", "Lt") and len(e.values) == 1:
                try:
                    nums[ti, ei] = float(int(e.values[0]))
                except ValueError:
                    nums[ti, ei] = np.nan

    def _pack_tolerations(self, pod: Pod, out: dict[str, np.ndarray]) -> None:
        TO = self.caps.tolerations
        tols = pod.spec.tolerations
        if len(tols) > TO:
            raise CapacityError("tolerations", len(tols))
        out["tol_key"] = np.full((TO,), NONE, np.int32)
        out["tol_op"] = np.full((TO,), NONE, np.int32)
        out["tol_val"] = np.full((TO,), NONE, np.int32)
        out["tol_effect"] = np.full((TO,), NONE, np.int32)
        out["tol_valid"] = np.zeros((TO,), bool)
        for i, t in enumerate(tols):
            out["tol_valid"][i] = True
            out["tol_key"][i] = self._i(t.key) if t.key else NONE
            out["tol_op"][i] = (F.TOL_EXISTS if t.operator == "Exists"
                                else F.TOL_EQUAL)
            out["tol_val"][i] = self._i(t.value)
            out["tol_effect"][i] = (F.effect_id(t.effect) if t.effect else NONE)

    def _pack_host_ports(self, pod: Pod, out: dict[str, np.ndarray]) -> None:
        HP = self.caps.pod_ports
        ports = [(p.host_ip, p.protocol, p.host_port)
                 for c in pod.spec.containers for p in c.ports if p.host_port > 0]
        if len(ports) > HP:
            raise CapacityError("pod_ports", len(ports))
        out["hp_ip"] = np.full((HP,), NONE, np.int32)
        out["hp_proto"] = np.full((HP,), NONE, np.int32)
        out["hp_port"] = np.full((HP,), NONE, np.int32)
        for i, (ip, proto, port) in enumerate(ports):
            out["hp_ip"][i] = self._i(ip or "0.0.0.0")
            out["hp_proto"][i] = self._i(proto or "TCP")
            out["hp_port"][i] = port

    def _pack_pod_affinity(self, pod: Pod, pi: PodInfo,
                           out: dict[str, np.ndarray]) -> None:
        self._pack_term_group(pi.required_affinity_terms, None, pod, "aff", out)
        self._pack_term_group(pi.required_anti_affinity_terms, None, pod,
                              "anti", out)
        self._pack_term_group(
            [w.pod_affinity_term for w in pi.preferred_affinity_terms],
            [w.weight for w in pi.preferred_affinity_terms], pod, "paff", out)
        self._pack_term_group(
            [w.pod_affinity_term for w in pi.preferred_anti_affinity_terms],
            [w.weight for w in pi.preferred_anti_affinity_terms], pod,
            "panti", out)
        # first-pod-of-group rule (satisfyPodAffinity, filtering.go): does the
        # pod match ALL of its own required affinity terms?
        out["aff_self_match"] = np.bool_(
            bool(pi.required_affinity_terms)
            and all(self.term_matches_pod(t, pod, pod)
                    for t in pi.required_affinity_terms))

    def _pack_spread(self, pod: Pod, out: dict[str, np.ndarray]) -> None:
        caps = self.caps
        C, MS = caps.spread_constraints, caps.aff_sel
        out["tsc_tk"] = np.full((C,), NONE, np.int32)
        out["tsc_max_skew"] = np.zeros((C,), np.int32)
        out["tsc_hard"] = np.zeros((C,), bool)
        out["tsc_min_domains"] = np.zeros((C,), np.int32)
        out["tsc_sel_cols"] = np.full((C, MS), NONE, np.int32)
        out["tsc_sel_ops"] = np.full((C, MS), NONE, np.int32)
        out["tsc_sel_vals"] = np.full((C, MS, self.caps.aff_sel_vals), NONE,
                                      np.int32)
        out["tsc_honor_affinity"] = np.ones((C,), bool)
        out["tsc_honor_taints"] = np.zeros((C,), bool)
        tscs = pod.spec.topology_spread_constraints
        if len(tscs) > C:
            raise CapacityError("spread_constraints", len(tscs))
        for i, t in enumerate(tscs):
            out["tsc_tk"][i] = self.topo_col(t.topology_key)
            self._used_tks.add(int(out["tsc_tk"][i]))
            out["tsc_max_skew"][i] = t.max_skew
            out["tsc_hard"][i] = t.when_unsatisfiable == "DoNotSchedule"
            out["tsc_min_domains"][i] = t.min_domains or 0
            # nil selector = labels.Nothing(): matches no pod, selfMatchNum 0
            # (filtering.go:311); matchLabelKeys merge as In requirements
            # (strategy.go applyMatchLabelKeys — spread has no mismatch keys)
            exprs = self._effective_exprs(t.label_selector,
                                          pod.metadata.labels,
                                          t.match_label_keys, [])
            self._pack_exprs(exprs, out["tsc_sel_cols"], out["tsc_sel_ops"],
                             out["tsc_sel_vals"], i)
            out["tsc_honor_affinity"][i] = t.node_affinity_policy == "Honor"
            out["tsc_honor_taints"][i] = t.node_taints_policy == "Honor"

    def pack_batch_blobs(self, pods: list[Pod], batch_size: int,
                         fields: tuple[str, ...] | None = None) -> PodBlobs:
        """Pack pods into a [B]-batched PodBlobs (2 device transfers), padding
        to batch_size with invalid rows. With ``fields`` the blobs carry only
        that subset (BlobCodec.subset_layout) — the launch splices the rest
        from the device-resident template (pod_template_blobs), keeping the
        per-batch host->device transfer proportional to what the workload
        uses instead of the full schema."""
        if fields is None:
            self._batch_prepass(pods, batch_size)
            f32, i32 = self.pod_codec.alloc(batch_size)
            tf32, ti32 = self._pod_template()
            f32[: len(pods)] = tf32
            i32[: len(pods)] = ti32
            for b, pod in enumerate(pods):
                self.pod_codec.pack_into(f32[b], i32[b],
                                         self.pack_pod(pod, active_only=True))
            # padding rows stay zeroed => valid False
            return PodBlobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32))
        f32, i32 = self._pack_batch_np(pods, batch_size, fields)
        return PodBlobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32))

    def _batch_prepass(self, pods: list[Pod], batch_size: int) -> None:
        """Validate + register every batch pod's label keys so a term packed
        for pod i can reference a column pod j>i carries, and note every
        batch namespace so term nsSelector unrolls see all of them."""
        if not pods:
            raise ValueError("empty batch")
        if len(pods) > batch_size:
            raise ValueError(f"{len(pods)} pods exceed batch_size {batch_size}")
        for pod in pods:
            self._note_namespace(pod.metadata.namespace)
            for k in pod.metadata.labels:
                self.pod_label_col(k)

    @staticmethod
    def _plain_pod_key(pod: Pod):
        """Content key for the plain-pod packed-row cache, or None when
        the pod uses any feature beyond (namespace, priority, labels-free
        containers with resource requests) — deployment-shaped batches are
        thousands of pods identical up to name/uid, and re-deriving the
        whole row per pod was the dominant host pack cost."""
        s = pod.spec
        if (s.affinity is not None or s.node_selector or s.tolerations
                or s.topology_spread_constraints or s.init_containers
                or s.overhead or s.volumes or s.resource_claims
                or s.scheduling_gates or s.node_name
                or pod.status.nominated_node_name or pod.metadata.labels):
            return None
        for c in s.containers:
            if c.ports:
                return None
        return (pod.metadata.namespace, s.priority,
                tuple((c.image, tuple(sorted(c.resources.requests.items())))
                      for c in s.containers))

    def _pack_batch_np(self, pods: list[Pod], batch_size: int,
                       fields: tuple[str, ...]
                       ) -> tuple[np.ndarray, np.ndarray]:
        """Subset-packed batch rows as host arrays (pack_batch_blobs body;
        prepare_launch also hashes these rows for topology-group dedup).

        Plain pods (no features beyond requests) share a cached packed row
        per content key; only the identity columns (name_id, uid_id) are
        patched per pod."""
        self._batch_prepass(pods, batch_size)
        tmpl = self._subset_tmpl.get(fields)
        if tmpl is None:
            tf32, ti32 = self._pod_template()
            tmpl = self.pod_codec.subset_template(fields, tf32, ti32)
            self._subset_tmpl[fields] = tmpl
        f32, i32 = self.pod_codec.alloc_subset(fields, batch_size)
        f32[: len(pods)] = tmpl[0]
        i32[: len(pods)] = tmpl[1]
        _f_off, i_off, _, _ = self.pod_codec.subset_layout(fields)
        # identity patch offsets; a subset omitting them (any-subset is a
        # legal BlobCodec contract) just skips the cache fast path
        name_ent = i_off.get("name_id")
        uid_ent = i_off.get("uid_id")
        cacheable = name_ent is not None and uid_ent is not None
        cache = self._plain_rows.setdefault(fields, {})
        for b, pod in enumerate(pods):
            key = self._plain_pod_key(pod) if cacheable else None
            row = cache.get(key) if key is not None else None
            if row is not None:
                f32[b] = row[0]
                i32[b] = row[1]
            else:
                self.pod_codec.pack_into_subset(
                    fields, f32[b], i32[b],
                    self.pack_pod(pod, active_only=True))
                if key is not None:
                    if len(cache) > 4096:
                        cache.clear()
                    cache[key] = (f32[b].copy(), i32[b].copy())
            if cacheable:
                i32[b, name_ent[0]] = self._i(pod.metadata.name)
                i32[b, uid_ent[0]] = self._i(pod.metadata.uid)
        return f32, i32

    # identity fields excluded from the topology-group signature: two pods
    # differing ONLY in these compute identical topology statics (name/uid
    # feed tie-breaking and diagnostics, which stay per-pod). Exception:
    # NOMINATED pods keep their uid in the signature — the pod table's
    # self-exclusion (topology.table_mask) compares table-entry uids against
    # the scheduled pod's uid, so a nominated pod sharing a group with
    # another pod would inherit the representative's self-exclusion.
    GROUP_IGNORED_FIELDS = ("name_id", "uid_id")

    def _batch_groups(self, f32: np.ndarray, i32: np.ndarray, n_pods: int,
                      fields: tuple[str, ...],
                      max_groups: int | None = None
                      ) -> tuple[np.ndarray, np.ndarray, int] | None:
        """Dedup batch rows into topology groups: (gid [B], rep [G_cap],
        g_cap). Pods with byte-identical packed rows (minus identity fields)
        share all topology statics and pairwise term matches, so the device
        computes them once per GROUP (pipeline phase-1/scan); padding rows
        form their own group.

        ``max_groups`` (probe mode): bail out with None as soon as the
        distinct-row count (padding group included) would exceed it, so a
        heterogeneous batch doesn't pay full per-row hashing for a result
        the caller will discard."""
        batch_size = f32.shape[0]
        f_off, i_off, _, _ = self.pod_codec.subset_layout(fields)
        fh = f32[:n_pods]
        ih = i32[:n_pods].copy()
        nominated = None
        if "nominated_row" in i_off:
            noff, _ = i_off["nominated_row"]
            nominated = ih[:, noff] != NONE
        for name in self.GROUP_IGNORED_FIELDS:
            if name in i_off:
                off, size = i_off[name]
                if nominated is None:
                    ih[:, off:off + size] = 0
                else:   # keep identity for nominated pods (see above)
                    ih[~nominated, off:off + size] = 0
        gid = np.zeros((batch_size,), np.int32)
        seen: dict[bytes, int] = {}
        reps: list[int] = []
        # the padding group (if any) counts against max_groups up front
        cap = (max_groups - (1 if n_pods < batch_size else 0)
               if max_groups is not None else None)
        for b in range(n_pods):
            key = fh[b].tobytes() + ih[b].tobytes()
            g = seen.get(key)
            if g is None:
                g = len(reps)
                if cap is not None and g >= cap:
                    return None
                seen[key] = g
                reps.append(b)
            gid[b] = g
        if n_pods < batch_size:          # padding rows: one shared group
            gid[n_pods:] = len(reps)
            reps.append(n_pods)
        # min 2: a full homogeneous batch (no padding group) would otherwise
        # bucket to g_cap=1 while partial batches of the same workload get 2,
        # flapping the static arg and recompiling between them
        g_cap = 2
        while g_cap < len(reps):
            g_cap *= 2
        rep = np.full((g_cap,), reps[0], np.int32)
        rep[: len(reps)] = reps
        return gid, rep, g_cap

    def pack_batch(self, pods: list[Pod], batch_size: int) -> PodFeatures:
        """PodFeatures view of a packed batch (jitted unpack; test/tooling)."""
        return _unpack_pods_jit(self.pack_batch_blobs(pods, batch_size), self.caps)

    @staticmethod
    def batch_has_host_ports(pods: list[Pod]) -> bool:
        return any(p.host_port > 0 for pod in pods
                   for c in pod.spec.containers for p in c.ports)

    def pod_fields(self, active: tuple[str, ...],
                   topo: bool) -> tuple[str, ...]:
        """The PodFeatures fields this launch's kernels can read, given its
        active features — everything else rides the device-resident template
        instead of the (slow) host->device link. Sorted for a stable jit
        static-arg key."""
        fields = set(POD_CORE_FIELDS)
        for feat in active:
            fields.update(POD_FEATURE_FIELDS.get(feat, ()))
        if topo:
            fields.update(POD_TOPO_FIELDS)
        return tuple(sorted(fields))

    def pod_template_blobs(self) -> PodBlobs:
        """Device-resident 1-row full-schema template (pushed once).

        INVARIANT: _pod_tmpl_dev / _subset_tmpl are cached for the
        Mirror's lifetime. That is sound only because template content is
        state-independent (empty-pod defaults; the interner is append-only)
        and re-bucketing constructs a FRESH Mirror. An edit that makes
        _pod_template depend on mutable state must invalidate these."""
        if self._pod_tmpl_dev is None:
            f32, i32 = self._pod_template()
            self._pod_tmpl_dev = PodBlobs(f32=jnp.asarray(f32),
                                          i32=jnp.asarray(i32))
        return self._pod_tmpl_dev

    def launch_features(self, pods: list[Pod]) -> tuple[str, ...]:
        """STATIC activity flags for one launch (schedule_batch ``active``):
        a feature used by neither the batch nor any mirrored node compiles
        out of the launch program entirely — the workload-shaped analog of
        PreFilter-Skip, and the reason a constraint-free drain runs just the
        fit/utilization kernels."""
        feats = []
        full_aff = any_pin = False
        for pod in pods:
            aff = pod.spec.affinity
            na = aff.node_affinity if aff is not None else None
            if pod.spec.node_selector \
                    or (na is not None
                        and self._node_affinity_pin(na) is None):
                full_aff = True
                break
            if na is not None:
                any_pin = True
        if full_aff:
            feats.append("nodeaffinity")
        elif any_pin:
            # every affinity in the batch is a metadata.name pin: compile
            # only the [N] pin compare (the daemonset fast path)
            feats.append("nodeaffinity_pin")
        if self._rows_with_taints:
            feats.append("taints")
        if self._rows_with_ports or self.batch_has_host_ports(pods):
            feats.append("ports")
        if self._rows_with_images and any(
                c.image for pod in pods for c in pod.spec.containers):
            feats.append("images")
        return tuple(feats)

    def prepare_launch(self, pods: list[Pod], batch_size: int
                       ) -> LaunchSpec:
        """Everything one schedule_batch launch needs, in the right order:
        pods are packed BEFORE the cluster blobs are fetched, so a topology
        key first referenced by this batch has its backfilled topo_dom
        column on device for this very launch (not the next one)."""
        feats = self.launch_features(pods)
        enable = self.batch_has_topology(pods) or self.table_has_topology()
        pfields = self.pod_fields(feats, enable)
        f32, i32 = self._pack_batch_np(pods, batch_size, pfields)
        pblobs = PodBlobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32))
        gid = rep = None
        g_cap = 0
        if enable:
            # NOTE: g_cap deliberately has NO sticky high-water. Compiled
            # programs are cached per static key, so flapping between two
            # SEEN g_cap values costs nothing; padding every launch to a
            # past batch's group count would pay real per-launch compute
            # (a 100-namespace init phase would tax the whole homogeneous
            # measure phase at [G=128] statics). Hysteresis applies where
            # it prevents NEW shapes: d_cap across mirror rebuilds
            # (launch_d_cap / adopt_hysteresis).
            gid_np, rep_np, g_cap = self._batch_groups(
                f32, i32, len(pods), pfields)
            gid = jnp.asarray(gid_np)
            rep = jnp.asarray(rep_np)
        elif pods:
            # phase-1 static dedup for deployment-shaped NO-topology
            # batches: identical specs share all static filters/scores, so
            # the [B, N] phase-1 work collapses to [G, N] + a gather. Only
            # taken at a FIXED tiny group bucket — g_cap is a static jit
            # arg, and a fixed 8 keeps every batch of a workload (warmup,
            # full-size, the short tail batch) on the same compiled
            # program; spec-diverse batches bail out of the probe early
            # and take the per-pod path, also a stable program.
            probe = self._batch_groups(f32, i32, len(pods), pfields,
                                       max_groups=P1_DEDUP_GROUP_CAP)
            if probe is not None:
                gid_np, rep_np, _ = probe
                rep8 = np.full((P1_DEDUP_GROUP_CAP,), rep_np[0], np.int32)
                rep8[: len(rep_np)] = rep_np[: P1_DEDUP_GROUP_CAP]
                gid = jnp.asarray(gid_np)
                rep = jnp.asarray(rep8)
                g_cap = P1_DEDUP_GROUP_CAP
        return LaunchSpec(cblobs=self.to_blobs(), pblobs=pblobs,
                          enable_topology=enable,
                          d_cap=self.launch_d_cap(enable),
                          active=feats, pfields=pfields,
                          ptmpl=self.pod_template_blobs(),
                          gid=gid, rep=rep, g_cap=g_cap,
                          topo_soft=(enable and
                                     self.batch_topology_soft_only(pods)))
