"""The three-tier pending-pod queue with queueing hints.

Equivalent of /root/reference/pkg/scheduler/backend/queue/
scheduling_queue.go:147-198 (PriorityQueue), active_queue.go (in-flight
pods + concurrent-event replay), backoff_queue.go (exponential per-pod
backoff), and the event-driven requeue machinery
(MoveAllToActiveOrBackoffQueue :1129, isPodWorthRequeuing :428).

Tiers:
- activeQ    — heap ordered by the profile's QueueSort (priority desc, FIFO)
- backoffQ   — heap ordered by backoff expiry; error backoff is tracked
               separately from unschedulable backoff (types.go:394-404)
- unschedulablePods — map of pods waiting for a cluster event a QueueingHint
               says could make them schedulable

The TPU-build extension: ``pop_batch(n)`` drains up to n pods in one call —
the batch axis of the device pipeline (SURVEY.md north star) — marking all
of them in-flight with concurrent-event replay per pod.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.backend.heap import Heap
from kubernetes_tpu.framework.interface import (
    ClusterEvent,
    ClusterEventWithHint,
    EventResource as R,
    QueueingHint,
    Status,
)

# reference defaults (scheduling_queue.go:63-80)
DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_MAX_IN_UNSCHEDULABLE_DURATION = 5 * 60.0


@dataclass
class QueuedPodInfo:
    """framework.QueuedPodInfo (types.go:377)."""

    pod: Pod
    timestamp: float = 0.0                 # last queue entry
    initial_attempt_timestamp: Optional[float] = None
    attempts: int = 0
    unschedulable_count: int = 0
    consecutive_errors_count: int = 0
    unschedulable_plugins: set[str] = field(default_factory=set)
    pending_plugins: set[str] = field(default_factory=set)
    gated_plugin: str = ""
    # park-index bookkeeping: the (resource, action) keys this pod is
    # filed under while parked (see PriorityQueue._park)
    park_keys: list = field(default_factory=list)
    # host Filter rejects from the last attempt (plugin -> node count);
    # merged into the failure diagnosis alongside device reject_counts
    host_reject_counts: dict[str, int] = field(default_factory=dict)

    @property
    def uid(self) -> str:
        return self.pod.metadata.uid

    def deep_copy(self) -> "QueuedPodInfo":
        return QueuedPodInfo(
            pod=self.pod, timestamp=self.timestamp,
            initial_attempt_timestamp=self.initial_attempt_timestamp,
            attempts=self.attempts,
            unschedulable_count=self.unschedulable_count,
            consecutive_errors_count=self.consecutive_errors_count,
            unschedulable_plugins=set(self.unschedulable_plugins),
            pending_plugins=set(self.pending_plugins),
            gated_plugin=self.gated_plugin)


class PriorityQueue:
    def __init__(self,
                 less_fn: Callable[[QueuedPodInfo, QueuedPodInfo], bool],
                 sort_key_fn: Optional[
                     Callable[[QueuedPodInfo], tuple]] = None,
                 pre_enqueue: Optional[Callable[[Pod], Status]] = None,
                 queueing_hints: Optional[
                     dict[str, list[ClusterEventWithHint]]] = None,
                 initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
                 max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
                 max_in_unschedulable: float =
                 DEFAULT_MAX_IN_UNSCHEDULABLE_DURATION,
                 now: Callable[[], float] = time.time):
        self._now = now
        self._less = less_fn
        self._pre_enqueue = pre_enqueue or (lambda pod: Status())
        # plugin name -> registered events+hints (buildQueueingHintMap)
        self._hints = queueing_hints or {}
        self._initial_backoff = initial_backoff
        self._max_backoff = max_backoff
        self._max_in_unschedulable = max_in_unschedulable

        self._active: Heap[QueuedPodInfo] = Heap(
            lambda qp: qp.uid, less_fn, sort_key_fn=sort_key_fn)
        self._backoff: Heap[QueuedPodInfo] = Heap(
            lambda qp: qp.uid,
            lambda a, b: self._backoff_expiry(a) < self._backoff_expiry(b),
            # expiry is a plain float: the backoff heap rides the native
            # engine (expiry recomputes on every add, same as less_fn did)
            sort_key_fn=lambda qp: (self._backoff_expiry(qp),))
        self._unschedulable: dict[str, QueuedPodInfo] = {}
        # gated pods (PreEnqueue rejections) live apart from unschedulable
        # ones: 10k parked gated pods must cost busy-path events nothing
        # (the SchedulingWhileGated workload's whole point)
        self._gated: dict[str, QueuedPodInfo] = {}
        # inverted requeue index over BOTH parked pools: (resource, action)
        # of every registered ClusterEvent of a pod's rejecting/gating
        # plugins -> uids. move_all touches only pods subscribed to a
        # matching event instead of sweeping O(parked) per event — the
        # index form of scheduling_queue.go:428's isPodWorthRequeuing
        # prefilter, needed because a Python sweep is ~100x the Go one.
        self._park_index: dict[tuple, set[str]] = {}
        self._park_all: set[str] = set()   # pods any event can requeue
        # in-flight machinery (active_queue.go:147-169): ONE shared event log
        # (seq, event, old, new) + per-pod start seq — appending an event is
        # O(1) regardless of how many pods are in flight (the reference's
        # shared inFlightEvents list, not a per-pod copy)
        self._in_flight: dict[str, int] = {}        # uid -> start seq
        self._events: list[tuple[int, ClusterEvent, object, object]] = []
        self._next_seq = 0
        self._moved_cycle = 0
        # event-burst coalescing window (ISSUE 15): non-None while a
        # caller batches requeue reaction across a burst (an eviction
        # flush's multi-delete wave) — see coalescing()
        self._coalesce: Optional[list] = None

    # ------------- backoff (backoff_queue.go:248) -------------

    def _backoff_duration(self, qp: QueuedPodInfo) -> float:
        """initial * 2^(count-1), capped; error backoff counts separately to
        protect the apiserver (types.go:394-404)."""
        count = max(qp.consecutive_errors_count, qp.unschedulable_count)
        if count == 0:
            return 0.0
        duration = self._initial_backoff * (2 ** (count - 1))
        return min(duration, self._max_backoff)

    def _backoff_expiry(self, qp: QueuedPodInfo) -> float:
        return qp.timestamp + self._backoff_duration(qp)

    def backoff_remaining(self, qp: QueuedPodInfo) -> float:
        return max(0.0, self._backoff_expiry(qp) - self._now())

    # ------------- add paths -------------

    def add(self, pod: Pod) -> None:
        """New pending pod from the informer (scheduling_queue.go Add)."""
        qp = QueuedPodInfo(pod=pod, timestamp=self._now(),
                           initial_attempt_timestamp=None)
        self._enqueue(qp)

    def _park(self, qp: QueuedPodInfo,
              pool: dict[str, QueuedPodInfo]) -> None:
        """File a pod in a parked pool + the inverted requeue index."""
        if qp.park_keys or qp.uid in self._park_all:
            # re-park without unpark would strand stale index entries
            self._unpark(qp)
        uid = qp.uid
        pool[uid] = qp
        plugins = set(qp.unschedulable_plugins)
        if qp.gated_plugin:
            plugins.add(qp.gated_plugin)
        keys = []
        wide = not plugins
        for plugin in plugins:
            regs = self._hints.get(plugin)
            if regs is None:
                # no registrations (extenders, out-of-tree): any event may
                # unstick it, like _worth_requeuing treats it
                wide = True
                continue
            for reg in regs:
                keys.append((reg.event.resource, reg.event.action_type))
        if wide:
            self._park_all.add(uid)
        for k in keys:
            self._park_index.setdefault(k, set()).add(uid)
        qp.park_keys = keys

    def _unpark(self, qp: QueuedPodInfo) -> None:
        uid = qp.uid
        self._park_all.discard(uid)
        for k in qp.park_keys:
            bucket = self._park_index.get(k)
            if bucket is not None:
                bucket.discard(uid)
                if not bucket:
                    del self._park_index[k]
        qp.park_keys = []

    def _pop_parked(self, uid: str) -> Optional[QueuedPodInfo]:
        qp = self._unschedulable.pop(uid, None)
        if qp is None:
            qp = self._gated.pop(uid, None)
        if qp is not None:
            self._unpark(qp)
        return qp

    def _enqueue(self, qp: QueuedPodInfo) -> None:
        """Run PreEnqueue gates; activeQ on success, gated pool if gated
        (scheduling_queue.go:538 runPreEnqueuePlugins)."""
        s = self._pre_enqueue(qp.pod)
        if s.is_success():
            qp.gated_plugin = ""
            self._active.add(qp)
            self._pop_parked(qp.uid)
            self._backoff.delete(qp.uid)
        else:
            qp.gated_plugin = s.plugin
            qp.unschedulable_plugins.add(s.plugin)
            self._park(qp, self._gated)

    def update(self, old: Pod, new: Pod) -> None:
        uid = new.metadata.uid
        for heap in (self._active, self._backoff):
            qp = heap.get(uid)
            if qp is not None:
                qp.pod = new
                heap.add(qp)
                return
        qp = self._unschedulable.get(uid) or self._gated.get(uid)
        if qp is not None:
            qp.pod = new
            if qp.gated_plugin:
                # gates may have been lifted by this update
                qp.timestamp = self._now()
                self._pop_parked(uid)
                self._enqueue(qp)
            return
        if uid not in self._in_flight:
            self.add(new)

    def delete(self, pod: Pod) -> None:
        uid = pod.metadata.uid
        self._active.delete(uid)
        self._backoff.delete(uid)
        self._pop_parked(uid)

    def drain_unowned(self, owns: Callable[[Pod], bool]) -> list[Pod]:
        """Scale-out rebalance support: remove and return every queued
        pod ``owns`` disclaims — active, backoff, unschedulable, and
        gated alike. The caller (the scheduler's slice sync) re-homes
        them; pods mid-cycle in ``_in_flight`` are left to finish and
        get fenced at bind if the slice really moved."""
        out: list[Pod] = []
        for heap in (self._active, self._backoff):
            for qp in list(heap.list()):
                if not owns(qp.pod):
                    heap.delete(qp.uid)
                    out.append(qp.pod)
        for pool in (self._unschedulable, self._gated):
            for uid, qp in list(pool.items()):
                if not owns(qp.pod):
                    self._pop_parked(uid)
                    out.append(qp.pod)
        return out

    # ------------- pop / in-flight -------------

    def pop(self) -> Optional[QueuedPodInfo]:
        qp = self._active.pop()
        if qp is None:
            return None
        qp.attempts += 1
        if qp.initial_attempt_timestamp is None:
            qp.initial_attempt_timestamp = self._now()
        self._in_flight[qp.uid] = self._next_seq
        return qp

    def pop_batch(self, n: int) -> list[QueuedPodInfo]:
        """Drain up to n pods for one device launch (the batch axis)."""
        out = []
        for _ in range(n):
            qp = self.pop()
            if qp is None:
                break
            out.append(qp)
        return out

    def done(self, uid: str) -> None:
        """Scheduling (+binding) finished; release in-flight events
        (schedule_one.go:305 via active_queue.go done)."""
        self._in_flight.pop(uid, None)
        self._trim_events()

    def _trim_events(self) -> None:
        """Drop log entries no in-flight pod can still replay. The min() scan
        is amortized: only when the log is empty-able or has grown past the
        trim threshold."""
        if not self._in_flight:
            self._events.clear()
        elif len(self._events) > 8192:
            low = min(self._in_flight.values())
            keep = [e for e in self._events if e[0] >= low]
            self._events = keep

    def in_flight_count(self) -> int:
        return len(self._in_flight)

    def is_parked(self, uid: str) -> bool:
        """True when the pod already re-entered a queue pool (active,
        backoff, unschedulable, or gated) — i.e. some failure handler
        owns it and it must not be driven again this cycle (the fault
        containment path uses this to skip already-parked batch peers)."""
        return (uid in self._active or uid in self._backoff
                or uid in self._unschedulable or uid in self._gated)

    # ------------- unschedulable / requeue -------------

    def add_unschedulable_if_not_present(self, qp: QueuedPodInfo,
                                         pod_scheduling_cycle: int = 0
                                         ) -> None:
        """Back from a failed cycle (scheduling_queue.go:824): replay events
        that arrived while in flight; if any hints QUEUE, skip the
        unschedulable pool and go straight to backoff/active."""
        uid = qp.uid
        start = self._in_flight.pop(uid, None)
        qp.timestamp = self._now()
        if uid in self._active or uid in self._backoff \
                or uid in self._unschedulable or uid in self._gated:
            self._trim_events()
            return
        if start is not None:
            for seq, event, old_obj, new_obj in self._events:
                if seq >= start and self._worth_requeuing(qp, event, old_obj,
                                                          new_obj):
                    self._trim_events()
                    self._requeue(qp)
                    return
        self._trim_events()
        if qp.consecutive_errors_count > 0 and not qp.unschedulable_plugins:
            # error-class failure (apiserver hiccup, bind conflict): no
            # cluster event will "fix" it — retry after backoff
            # (scheduling_queue.go:861 rejectedByError -> backoffQ)
            self._requeue(qp)
            return
        self._park(qp, self._unschedulable)

    def activate(self, pods: list[Pod]) -> None:
        """Plugin-requested activation (scheduling_queue.go:684)."""
        for pod in pods:
            qp = self._pop_parked(pod.metadata.uid)
            if qp is None:
                qp = self._backoff.delete(pod.metadata.uid)
            if qp is not None:
                qp.timestamp = self._now()
                self._enqueue(qp)

    def _worth_requeuing(self, qp: QueuedPodInfo, event: ClusterEvent,
                         old_obj, new_obj) -> bool:
        """isPodWorthRequeuing (scheduling_queue.go:428): consult the hint
        fns registered by the plugins that rejected this pod."""
        if not qp.unschedulable_plugins:
            return True  # rejected with no attribution: requeue on anything
        for plugin in qp.unschedulable_plugins:
            regs = self._hints.get(plugin)
            if regs is None:
                # a rejector with NO registrations (extenders, out-of-tree
                # plugins) cannot describe what unsticks its pods — requeue
                # on any event, like the reference treats extender rejects
                return True
            for reg in regs:
                if not reg.event.match(event):
                    continue
                if reg.queueing_hint_fn is None:
                    return True
                if reg.queueing_hint_fn(qp.pod, old_obj,
                                        new_obj) == QueueingHint.QUEUE:
                    return True
        return False

    def _requeue(self, qp: QueuedPodInfo) -> None:
        """To activeQ if backoff is over, else backoffQ
        (scheduling_queue.go:1139-1210 movePodsToActiveOrBackoffQueue)."""
        if qp.gated_plugin:
            self._park(qp, self._gated)
            return
        if self._backoff_expiry(qp) <= self._now():
            self._enqueue(qp)
        else:
            s = self._pre_enqueue(qp.pod)
            if s.is_success():
                self._backoff.add(qp)
            else:
                qp.gated_plugin = s.plugin
                self._park(qp, self._gated)

    def move_all_to_active_or_backoff(self, event: ClusterEvent,
                                      old_obj=None, new_obj=None) -> int:
        """A cluster event arrived (MoveAllToActiveOrBackoffQueue :1129).
        Also records the event in the shared in-flight log so any pod whose
        cycle fails can replay it."""
        if self._in_flight:
            self._events.append((self._next_seq, event, old_obj, new_obj))
            self._next_seq += 1
        self._moved_cycle += 1
        if self._coalesce is not None:
            # inside a coalescing window: the in-flight log above already
            # recorded the event; parked-pod reaction happens ONCE at
            # window close instead of per event
            self._coalesce.append((event, old_obj, new_obj))
            return 0
        moved = 0
        # candidates via the inverted index: distinct registered events are
        # few (tens), parked pods can be tens of thousands — only pods
        # whose plugins registered a MATCHING event are touched at all
        cands = set(self._park_all)
        for (res, action), uids in self._park_index.items():
            if ((res == R.WILDCARD or res == event.resource)
                    and action & event.action_type):
                cands |= uids
        for uid in cands:
            qp = self._gated.get(uid)
            if qp is not None:
                # gated pods re-run PreEnqueue instead of hints (the
                # matching registration got them here — e.g. the gates
                # plugin's gate-eliminated event, or DefaultPreemption's
                # victim-delete)
                s = self._pre_enqueue(qp.pod)
                if s.is_success():
                    self._pop_parked(uid)
                    qp.gated_plugin = ""
                    qp.timestamp = self._now()
                    self._enqueue(qp)
                    moved += 1
                continue
            qp = self._unschedulable.get(uid)
            if qp is None:
                continue
            if self._worth_requeuing(qp, event, old_obj, new_obj):
                self._pop_parked(uid)
                self._requeue(qp)
                moved += 1
        return moved

    def coalescing(self):
        """Context manager batching requeue reaction across an event
        BURST (an eviction flush's multi-delete wave, ISSUE 15): inside
        the window move_all_to_active_or_backoff only records events (the
        in-flight replay log is unaffected); the window close runs one
        pass where every parked candidate probes the whole burst at most
        once — O(affected pods) per wave instead of O(events x parked
        probes), and a gated pod re-runs its PreEnqueue gate once per
        wave instead of once per deletion."""
        import contextlib

        @contextlib.contextmanager
        def _window():
            if self._coalesce is not None:
                yield               # nested: the outer window owns it
                return
            self._coalesce = []
            try:
                yield
            finally:
                events, self._coalesce = self._coalesce, None
                self._move_all_batched(events)
        return _window()

    def _move_all_batched(self, events: list) -> int:
        if not events:
            return 0
        moved = 0
        cands = set(self._park_all)
        for (res, action), uids in self._park_index.items():
            for event, _old, _new in events:
                if ((res == R.WILDCARD or res == event.resource)
                        and action & event.action_type):
                    cands |= uids
                    break
        for uid in cands:
            qp = self._gated.get(uid)
            if qp is not None:
                s = self._pre_enqueue(qp.pod)
                if s.is_success():
                    self._pop_parked(uid)
                    qp.gated_plugin = ""
                    qp.timestamp = self._now()
                    self._enqueue(qp)
                    moved += 1
                continue
            qp = self._unschedulable.get(uid)
            if qp is None:
                continue
            for event, old_obj, new_obj in events:
                if self._worth_requeuing(qp, event, old_obj, new_obj):
                    self._pop_parked(uid)
                    self._requeue(qp)
                    moved += 1
                    break
        return moved

    # ------------- periodic flushes (scheduling_queue.go:378-386) -------------

    def flush_backoff_completed(self) -> int:
        """backoffQ -> activeQ for pods whose backoff expired (1s tick)."""
        moved = 0
        now = self._now()
        while True:
            head = self._backoff.peek()
            if head is None or self._backoff_expiry(head) > now:
                break
            self._backoff.pop()
            self._enqueue(head)
            moved += 1
        return moved

    def flush_unschedulable_timeout(self) -> int:
        """unschedulable pods stuck longer than the timeout requeue
        unconditionally (30s tick; 5min default timeout)."""
        now = self._now()
        moved = 0
        # gated pods are exempt: no event, no timeout ungates them
        # (the reference's flushUnschedulablePodsLeftover skips gated too)
        for uid in list(self._unschedulable):
            qp = self._unschedulable[uid]
            if now - qp.timestamp >= self._max_in_unschedulable:
                self._pop_parked(uid)
                self._requeue(qp)
                moved += 1
        return moved

    # ------------- introspection -------------

    def pending_counts(self) -> dict[str, int]:
        """pending_pods gauge split by queue (metrics.go:201)."""
        return {
            "active": len(self._active),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable),
            "gated": len(self._gated),
        }

    def __len__(self) -> int:
        return (len(self._active) + len(self._backoff)
                + len(self._unschedulable) + len(self._gated))
