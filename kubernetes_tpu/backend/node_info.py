"""NodeInfo / PodInfo — the per-node scheduling view the cache maintains.

Host-side equivalent of ``framework.NodeInfo``
(/root/reference/pkg/scheduler/framework/types.go:780: node, Pods,
PodsWithAffinity, PodsWithRequiredAntiAffinity, UsedPorts, Requested,
NonZeroRequested, Allocatable, ImageStates, Generation) and
``framework.PodInfo`` (types.go:458: pod + pre-parsed affinity terms +
cached resource request).

These are the rows that get packed into the dense HBM feature tensor by
``kubernetes_tpu.backend.mirror``; ``generation`` drives the incremental
row-update diff exactly like the reference's incremental snapshot
(cache.go:186 UpdateSnapshot).
"""

from __future__ import annotations

import itertools
from typing import Optional

from kubernetes_tpu.api.objects import (
    Node,
    Pod,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.api.resources import Resource, pod_request

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


class PodInfo:
    """Pod plus pre-computed scheduling state (parsed affinity terms, cached
    resource request) so per-cycle work never re-parses specs."""

    __slots__ = (
        "pod",
        "required_affinity_terms",
        "required_anti_affinity_terms",
        "preferred_affinity_terms",
        "preferred_anti_affinity_terms",
        "request",
        "non_zero_request",
    )

    def __init__(self, pod: Pod):
        self.pod = pod
        aff = pod.spec.affinity
        self.required_affinity_terms: list[PodAffinityTerm] = (
            list(aff.pod_affinity.required) if aff and aff.pod_affinity else []
        )
        self.required_anti_affinity_terms: list[PodAffinityTerm] = (
            list(aff.pod_anti_affinity.required) if aff and aff.pod_anti_affinity else []
        )
        self.preferred_affinity_terms: list[WeightedPodAffinityTerm] = (
            list(aff.pod_affinity.preferred) if aff and aff.pod_affinity else []
        )
        self.preferred_anti_affinity_terms: list[WeightedPodAffinityTerm] = (
            list(aff.pod_anti_affinity.preferred) if aff and aff.pod_anti_affinity else []
        )
        self.request = pod_request(pod)
        self.non_zero_request = pod_request(pod, non_zero=True)

    def update(self, pod: Pod) -> "PodInfo":
        return PodInfo(pod)


class HostPortInfo:
    """(ip, protocol, port) occupancy with 0.0.0.0 wildcard conflict semantics
    (types.go:1291 HostPortInfo)."""

    WILDCARD = "0.0.0.0"

    def __init__(self) -> None:
        # ip -> set of (protocol, port)
        self.ports: dict[str, set[tuple[str, int]]] = {}

    @staticmethod
    def _sanitize(ip: str, protocol: str) -> tuple[str, str]:
        return (ip or HostPortInfo.WILDCARD, protocol or "TCP")

    def add(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip, protocol = self._sanitize(ip, protocol)
        self.ports.setdefault(ip, set()).add((protocol, port))

    def remove(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip, protocol = self._sanitize(ip, protocol)
        s = self.ports.get(ip)
        if s is not None:
            s.discard((protocol, port))
            if not s:
                del self.ports[ip]

    def conflicts(self, ip: str, protocol: str, port: int) -> bool:
        """True if (ip, protocol, port) clashes with an existing entry.
        Wildcard IP on either side conflicts with any IP (types.go CheckConflict)."""
        if port <= 0:
            return False
        ip, protocol = self._sanitize(ip, protocol)
        key = (protocol, port)
        if ip == self.WILDCARD:
            return any(key in s for s in self.ports.values())
        return key in self.ports.get(ip, ()) or key in self.ports.get(self.WILDCARD, ())

    def clone(self) -> "HostPortInfo":
        c = HostPortInfo()
        c.ports = {ip: set(s) for ip, s in self.ports.items()}
        return c

    def __len__(self) -> int:
        return sum(len(s) for s in self.ports.values())


class NodeInfo:
    """Aggregated scheduling state for one node."""

    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "pods_with_required_anti_affinity",
        "used_ports",
        "requested",
        "non_zero_requested",
        "allocatable",
        "image_sizes",
        "generation",
    )

    def __init__(self, node: Optional[Node] = None):
        self.node = node
        self.pods: list[PodInfo] = []
        self.pods_with_affinity: list[PodInfo] = []
        self.pods_with_required_anti_affinity: list[PodInfo] = []
        self.used_ports = HostPortInfo()
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        self.image_sizes: dict[str, int] = {}
        self.generation = next_generation()
        if node is not None:
            self.set_node(node)

    @property
    def name(self) -> str:
        return self.node.metadata.name if self.node else ""

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = Resource.from_map(node.status.allocatable)
        self.image_sizes = {
            name: img.size_bytes for img in node.status.images for name in img.names
        }
        self.generation = next_generation()

    def remove_node(self) -> None:
        """Node object deleted but pods remain (cache.go RemoveNode keeps the
        nodeinfo while pods are still assigned)."""
        self.node = None
        self.generation = next_generation()

    @staticmethod
    def _has_affinity(pi: PodInfo) -> bool:
        return bool(pi.required_affinity_terms or pi.preferred_affinity_terms
                    or pi.required_anti_affinity_terms
                    or pi.preferred_anti_affinity_terms)

    def add_pod(self, pod: Pod | PodInfo) -> None:
        pi = pod if isinstance(pod, PodInfo) else PodInfo(pod)
        self.pods.append(pi)
        if self._has_affinity(pi):
            self.pods_with_affinity.append(pi)
        if pi.required_anti_affinity_terms:
            self.pods_with_required_anti_affinity.append(pi)
        self.requested.add(pi.request)
        self.non_zero_requested.add(pi.non_zero_request)
        for c in pi.pod.spec.containers:
            for p in c.ports:
                if p.host_port > 0:
                    self.used_ports.add(p.host_ip, p.protocol, p.host_port)
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        uid = pod.metadata.uid
        for i, pi in enumerate(self.pods):
            if pi.pod.metadata.uid == uid:
                del self.pods[i]
                self.pods_with_affinity = [
                    p for p in self.pods_with_affinity if p.pod.metadata.uid != uid
                ]
                self.pods_with_required_anti_affinity = [
                    p for p in self.pods_with_required_anti_affinity
                    if p.pod.metadata.uid != uid
                ]
                self.requested.sub(pi.request)
                self.non_zero_requested.sub(pi.non_zero_request)
                for c in pi.pod.spec.containers:
                    for prt in c.ports:
                        if prt.host_port > 0:
                            self.used_ports.remove(prt.host_ip, prt.protocol, prt.host_port)
                self.generation = next_generation()
                return True
        return False

    def snapshot(self) -> "NodeInfo":
        """Shallow clone for the immutable per-cycle snapshot: lists and
        aggregates copied, PodInfo objects shared (they are immutable)."""
        c = NodeInfo.__new__(NodeInfo)
        c.node = self.node
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        c.used_ports = self.used_ports.clone()
        c.requested = self.requested.clone()
        c.non_zero_requested = self.non_zero_requested.clone()
        c.allocatable = self.allocatable.clone()
        c.image_sizes = dict(self.image_sizes)
        c.generation = self.generation
        return c
