"""Zone-aware node ordering.

Equivalent of /root/reference/pkg/scheduler/backend/cache/node_tree.go: nodes
are grouped by their (region, zone) key and listed round-robin across zones so
the snapshot's node order naturally spreads scheduling across zones
(node_tree.go:119-143 list()).
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import LABEL_REGION, LABEL_ZONE, Node


def zone_key(node: Node) -> str:
    region = node.metadata.labels.get(LABEL_REGION, "")
    zone = node.metadata.labels.get(LABEL_ZONE, "")
    return f"{region}:\x00:{zone}"


class NodeTree:
    def __init__(self) -> None:
        self._zones: dict[str, list[str]] = {}
        self.num_nodes = 0

    def add_node(self, node: Node) -> None:
        zone = zone_key(node)
        names = self._zones.setdefault(zone, [])
        if node.metadata.name in names:
            return
        names.append(node.metadata.name)
        self.num_nodes += 1

    def remove_node(self, node: Node) -> bool:
        zone = zone_key(node)
        names = self._zones.get(zone)
        if names and node.metadata.name in names:
            names.remove(node.metadata.name)
            if not names:
                del self._zones[zone]
            self.num_nodes -= 1
            return True
        return False

    def update_node(self, old: Node, new: Node) -> None:
        if zone_key(old) == zone_key(new):
            return
        self.remove_node(old)
        self.add_node(new)

    def list(self) -> list[str]:
        """Round-robin across zones (node_tree.go:119): one node from each
        zone per round, exhausted zones dropped from the rotation."""
        out: list[str] = []
        iters = [iter(names) for names in self._zones.values()]
        while iters:
            alive = []
            for it in iters:
                v = next(it, None)
                if v is not None:
                    out.append(v)
                    alive.append(it)
            iters = alive
        return out
