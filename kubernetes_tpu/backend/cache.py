"""Authoritative cluster-state cache with assumed pods and incremental snapshot.

Equivalent of /root/reference/pkg/scheduler/backend/cache/cache.go: confirmed
(informer-delivered) plus *assumed* pods (optimistically placed by the
scheduling cycle before the binding round-trips, cache.go:361 AssumePod);
an MRU doubly-linked NodeInfo list ordered by ``generation`` so the per-cycle
snapshot refresh touches only changed nodes (cache.go:186 UpdateSnapshot,
moveNodeInfoToHead:113); TTL-based assumed-pod expiry (cleanupAssumedPods:730).

Thread model mirrors the reference: informer event handlers and the scheduling
loop both call in under one lock; the scheduling loop's snapshot is read
lock-free after update_snapshot returns.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.backend.node_info import NodeInfo, next_generation
from kubernetes_tpu.backend.node_tree import NodeTree
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.storage import RvTooOld


@dataclass
class _PodState:
    pod: Pod
    assumed: bool = False
    deadline: Optional[float] = None  # set by finish_binding when ttl > 0
    binding_finished: bool = False


@dataclass
class DriftReport:
    """Structured cache-vs-hub diff (the comparer's findings, typed so
    the drift sentinel can repair them surgically instead of re-listing
    the world into a fresh cache)."""

    nodes_stale: list = field(default_factory=list)      # names, cache-only
    nodes_missing: list = field(default_factory=list)    # Nodes, hub-only
    pods_stale: list = field(default_factory=list)       # Pods, cache-only
    pods_missing: list = field(default_factory=list)     # Pods, hub-only
    pods_misplaced: list = field(default_factory=list)   # (cached, hub) Pods
    # the hub revision this report is consistent at: the NEXT sentinel
    # pass diffs journal changes after it instead of re-LISTing the
    # cluster (None when the hub cannot answer incrementally)
    rv: object = None
    incremental: bool = False

    def count(self) -> int:
        return (len(self.nodes_stale) + len(self.nodes_missing)
                + len(self.pods_stale) + len(self.pods_missing)
                + len(self.pods_misplaced))

    def render(self) -> list[str]:
        """The comparer's human-readable lines (SIGUSR2 debug format)."""
        out = []
        for name in self.nodes_stale:
            out.append(f"node {name} in cache but not in apiserver")
        for node in self.nodes_missing:
            out.append(f"node {node.metadata.name} in apiserver but "
                       "not in cache")
        for pod in self.pods_stale:
            out.append(f"pod {pod.key()} in cache but not bound "
                       "in apiserver")
        for pod in self.pods_missing:
            out.append(f"pod {pod.key()} bound in apiserver but "
                       "not in cache")
        for cached, p in self.pods_misplaced:
            out.append(f"pod {p.key()} on {p.spec.node_name} in apiserver "
                       f"but {cached.spec.node_name} in cache")
        return out


class _NodeInfoListItem:
    __slots__ = ("info", "next", "prev")

    def __init__(self, info: NodeInfo):
        self.info = info
        self.next: Optional[_NodeInfoListItem] = None
        self.prev: Optional[_NodeInfoListItem] = None


class Cache:
    def __init__(self, ttl: float = 0.0, now: Callable[[], float] = time.time):
        """ttl: seconds an assumed pod survives after finish_binding before
        being reaped (0 = never expire, the reference default
        scheduler.go:58-62)."""
        self._lock = threading.RLock()
        self._ttl = ttl
        self._now = now
        self._nodes: dict[str, _NodeInfoListItem] = {}
        self._head: Optional[_NodeInfoListItem] = None
        self._node_tree = NodeTree()
        self._pod_states: dict[str, _PodState] = {}  # uid -> state
        self._assumed_pods: set[str] = set()
        self._namespaces: dict[str, dict[str, str]] = {}  # name -> labels
        self._ns_generation = 0
        # bumped when the set of nodes (or node-less nodeinfos) changes, so
        # update_snapshot's no-change fast path can skip the removal scan
        self._node_set_version = 0

    # ---------------- internal list maintenance ----------------

    def _move_to_head(self, item: _NodeInfoListItem) -> None:
        if item is self._head:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if self._head is not None:
            self._head.prev = item
        item.prev = None
        item.next = self._head
        self._head = item

    def _remove_from_list(self, item: _NodeInfoListItem) -> None:
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if item is self._head:
            self._head = item.next
        item.prev = item.next = None

    def _get_or_create(self, node_name: str) -> _NodeInfoListItem:
        item = self._nodes.get(node_name)
        if item is None:
            item = _NodeInfoListItem(NodeInfo())
            self._nodes[node_name] = item
            # imaginary node (pod observed before its node): park at head
            if self._head is not None:
                self._head.prev = item
            item.next = self._head
            self._head = item
        return item

    # ---------------- node ops ----------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            item = self._get_or_create(node.metadata.name)
            self._node_tree.add_node(node)
            item.info.set_node(node)
            self._node_set_version += 1
            self._move_to_head(item)

    def update_node(self, old: Node, new: Node) -> None:
        with self._lock:
            item = self._get_or_create(new.metadata.name)
            self._node_tree.update_node(old, new)
            item.info.set_node(new)
            self._node_set_version += 1
            self._move_to_head(item)

    def remove_node(self, node: Node) -> None:
        with self._lock:
            item = self._nodes.get(node.metadata.name)
            if item is None:
                return
            self._node_set_version += 1
            self._node_tree.remove_node(node)
            if item.info.pods:
                # pods still assigned: keep the nodeinfo, drop the node object
                item.info.remove_node()
                self._move_to_head(item)
            else:
                self._remove_from_list(item)
                del self._nodes[node.metadata.name]

    def node_info(self, name: str):
        """The LIVE NodeInfo aggregate for one node, or None when the cache
        has never seen it. A node-less info (node deleted, assumed pods
        still draining) is returned as-is with ``info.node is None`` — the
        caller (Mirror.patch_node) treats that like a removal, matching
        update_snapshot's exclusion of node-less infos. The object is the
        cache's mutable truth: read it under the scheduler's event lock
        and don't hold it across handler returns."""
        with self._lock:
            item = self._nodes.get(name)
            return item.info if item is not None else None

    # ---------------- namespace ops ----------------

    def set_namespace(self, name: str, labels: dict[str, str]) -> None:
        """Add or update a namespace's labels (nsLister feed for affinity
        namespaceSelector unrolling)."""
        with self._lock:
            if self._namespaces.get(name) != labels:
                self._namespaces[name] = dict(labels)
                self._ns_generation = next_generation()

    def remove_namespace(self, name: str) -> None:
        with self._lock:
            if self._namespaces.pop(name, None) is not None:
                self._ns_generation = next_generation()

    # ---------------- pod ops ----------------

    def _add_pod_to_node(self, pod: Pod) -> None:
        item = self._get_or_create(pod.spec.node_name)
        item.info.add_pod(pod)
        self._move_to_head(item)

    def _remove_pod_from_node(self, pod: Pod) -> None:
        item = self._nodes.get(pod.spec.node_name)
        if item is None:
            return
        item.info.remove_pod(pod)
        if item.info.node is None and not item.info.pods:
            self._remove_from_list(item)
            del self._nodes[pod.spec.node_name]
        else:
            self._move_to_head(item)

    def assume_pod(self, pod: Pod) -> None:
        """Optimistically place a pod on pod.spec.node_name before binding
        (cache.go:361). Raises if already in cache."""
        uid = pod.metadata.uid
        with self._lock:
            if uid in self._pod_states:
                raise KeyError(f"pod {pod.key()} already in cache")
            self._add_pod_to_node(pod)
            self._pod_states[uid] = _PodState(pod=pod, assumed=True)
            self._assumed_pods.add(uid)

    def finish_binding(self, pod: Pod) -> None:
        """Start the assumed pod's expiry clock (cache.go:376)."""
        with self._lock:
            st = self._pod_states.get(pod.metadata.uid)
            if st and st.assumed:
                st.binding_finished = True
                if self._ttl > 0:
                    st.deadline = self._now() + self._ttl

    def forget_pod(self, pod: Pod) -> None:
        """Undo an assume after reserve/permit/bind failure (cache.go:404)."""
        uid = pod.metadata.uid
        with self._lock:
            st = self._pod_states.get(uid)
            if st is None:
                return
            if not st.assumed:
                raise KeyError(f"pod {pod.key()} is confirmed, cannot forget")
            self._remove_pod_from_node(st.pod)
            del self._pod_states[uid]
            self._assumed_pods.discard(uid)

    def add_pod(self, pod: Pod) -> None:
        """Informer-confirmed assigned pod (cache.go AddPod): confirms an
        assumed pod or adds a new one."""
        uid = pod.metadata.uid
        with self._lock:
            st = self._pod_states.get(uid)
            if (st is not None and st.assumed
                    and st.pod.spec.node_name == pod.spec.node_name):
                # confirm on the assumed node: the NodeInfo aggregates are
                # already right — swap the pod object in place WITHOUT
                # bumping the node generation, so the bind confirmation does
                # not force a second mirror row repack (the assume already
                # did one)
                item = self._nodes.get(pod.spec.node_name)
                if item is not None:
                    for pi in item.info.pods:
                        if pi.pod.metadata.uid == uid:
                            pi.pod = pod
                            break
                self._pod_states[uid] = _PodState(pod=pod)
                self._assumed_pods.discard(uid)
                return
            if st is not None:
                # informer truth wins, even if the node differs from what we
                # assumed; re-add of a confirmed pod is treated as an update
                self._remove_pod_from_node(st.pod)
            self._add_pod_to_node(pod)
            self._pod_states[uid] = _PodState(pod=pod)
            self._assumed_pods.discard(uid)

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            st = self._pod_states.get(new.metadata.uid)
            if st is None:
                self.add_pod(new)
                return
            self._remove_pod_from_node(st.pod)
            self._add_pod_to_node(new)
            self._pod_states[new.metadata.uid] = _PodState(pod=new)
            self._assumed_pods.discard(new.metadata.uid)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            st = self._pod_states.get(pod.metadata.uid)
            if st is None:
                return
            self._remove_pod_from_node(st.pod)
            del self._pod_states[pod.metadata.uid]
            self._assumed_pods.discard(pod.metadata.uid)

    def is_assumed_pod(self, pod: Pod) -> bool:
        with self._lock:
            return pod.metadata.uid in self._assumed_pods

    def get_pod(self, pod: Pod) -> Optional[Pod]:
        with self._lock:
            st = self._pod_states.get(pod.metadata.uid)
            return st.pod if st else None

    def cleanup_assumed_pods(self) -> list[Pod]:
        """Expire assumed pods whose deadline passed (cache.go:730). Returns
        the expired pods so the caller can requeue them."""
        expired = []
        with self._lock:
            now = self._now()
            for uid in list(self._assumed_pods):
                st = self._pod_states[uid]
                if st.binding_finished and st.deadline is not None and now >= st.deadline:
                    expired.append(st.pod)
                    self._remove_pod_from_node(st.pod)
                    del self._pod_states[uid]
                    self._assumed_pods.discard(uid)
        return expired

    # ---------------- snapshot ----------------

    def update_snapshot(self, snapshot: Snapshot) -> None:
        """Incremental refresh: walk the MRU list head-first, cloning only
        NodeInfos newer than the snapshot's generation (cache.go:186-280).
        Rebuilds the zone-interleaved list only when nodes were added/removed
        or an affinity-relevant change occurred, like the reference."""
        with self._lock:
            # no-change fast path: the MRU head carries the max generation,
            # so a clean cache makes the whole refresh O(1) — _ensure_synced
            # style callers (preemption mid-drain) can call this per pod
            if ((self._head is None
                 or self._head.info.generation <= snapshot.generation)
                    and snapshot.node_set_version == self._node_set_version
                    and snapshot.ns_generation == self._ns_generation):
                return
            snap_gen = snapshot.generation
            updated_affinity = False
            item = self._head
            latest = snap_gen
            while item is not None and item.info.generation > snap_gen:
                info = item.info
                latest = max(latest, info.generation)
                if info.node is not None:
                    existing = snapshot.node_info_map.get(info.name)
                    clone = info.snapshot()
                    if existing is None or bool(existing.pods_with_affinity) != bool(
                        clone.pods_with_affinity
                    ) or bool(existing.pods_with_required_anti_affinity) != bool(
                        clone.pods_with_required_anti_affinity
                    ):
                        updated_affinity = True
                    snapshot.node_info_map[info.name] = clone
                item = item.next

            # removals: any snapshot node no longer in the cache (or node-less)
            live = {name for name, it in self._nodes.items() if it.info.node is not None}
            removed = [n for n in snapshot.node_info_map if n not in live]
            for n in removed:
                del snapshot.node_info_map[n]

            if snapshot.ns_generation != self._ns_generation:
                snapshot.namespaces = {n: dict(l)
                                       for n, l in self._namespaces.items()}
                snapshot.ns_generation = self._ns_generation

            if removed or len(snapshot.node_info_list) != len(live) or updated_affinity:
                self._rebuild_lists(snapshot)
            else:
                # same node set: refresh list entries in place from the map
                snapshot.node_info_list = [
                    snapshot.node_info_map[ni.name] for ni in snapshot.node_info_list
                ]
                self._rebuild_affinity_lists(snapshot)
            snapshot.generation = latest
            snapshot.node_set_version = self._node_set_version
            snapshot.version += 1

    def _rebuild_lists(self, snapshot: Snapshot) -> None:
        snapshot.node_info_list = []
        for name in self._node_tree.list():
            ni = snapshot.node_info_map.get(name)
            if ni is not None:
                snapshot.node_info_list.append(ni)
        self._rebuild_affinity_lists(snapshot)

    @staticmethod
    def _rebuild_affinity_lists(snapshot: Snapshot) -> None:
        snapshot.have_pods_with_affinity_list = [
            ni for ni in snapshot.node_info_list if ni.pods_with_affinity
        ]
        snapshot.have_pods_with_required_anti_affinity_list = [
            ni for ni in snapshot.node_info_list if ni.pods_with_required_anti_affinity
        ]

    # ---------------- introspection (cache debugger, metrics) ----------------

    def node_count(self) -> int:
        with self._lock:
            return sum(1 for it in self._nodes.values() if it.info.node is not None)

    def pod_count(self) -> int:
        with self._lock:
            return sum(len(it.info.pods) for it in self._nodes.values())

    def assumed_pod_count(self) -> int:
        with self._lock:
            return len(self._assumed_pods)

    def drift_report(self, hub, since_rv: Optional[int] = None
                     ) -> DriftReport:
        """The cache comparer (backend/cache/debugger/comparer.go
        CompareNodes/ComparePods), structured: diff the scheduler's view
        against API truth. Assumed pods are expected to lead the API
        (they are the optimistic writes), so they are exempt from the
        bound-state checks.

        ``since_rv`` switches to INCREMENTAL mode: only objects the
        hub's journal says changed after that revision are compared —
        O(changes) instead of two O(cluster) LISTs per sentinel pass.
        Sound because drift is always the cache mis-applying (or
        missing) a hub mutation: an entry that was clean at the last
        full diff can only go bad through an event, and every event is
        in the journal. Raises RvTooOld when the gap was compacted (or
        the hub cannot answer) — the caller falls back to the full
        diff, the same ladder the watch-resume wire climbs. The
        returned report carries ``rv``, the next pass's resume point."""
        if since_rv is not None:
            return self._drift_report_incremental(hub, since_rv)
        report = DriftReport()
        # the watermark is taken BEFORE the LISTs: changes landing
        # during the diff re-examine next pass (harmless), never skip
        stats_fn = getattr(hub, "get_journal_stats", None)
        if stats_fn is not None:
            try:
                report.rv = stats_fn().get("rv")
            except Exception:  # noqa: BLE001 — stats are optional
                report.rv = None
        with self._lock:
            cached_nodes = set(self._nodes)
            cached_pods = {uid: st for uid, st in self._pod_states.items()}
            assumed = set(self._assumed_pods)
        hub_node_objs = {n.metadata.name: n for n in hub.list_nodes()}
        hub_nodes = set(hub_node_objs)
        report.nodes_stale = sorted(cached_nodes - hub_nodes)
        report.nodes_missing = [hub_node_objs[n]
                                for n in sorted(hub_nodes - cached_nodes)]
        hub_pods = {p.metadata.uid: p for p in hub.list_pods()
                    if p.spec.node_name}
        report.pods_stale = [
            cached_pods[uid].pod
            for uid in sorted(set(cached_pods) - set(hub_pods) - assumed)]
        for uid, p in sorted(hub_pods.items()):
            st = cached_pods.get(uid)
            if st is None:
                report.pods_missing.append(p)
            elif st.pod.spec.node_name != p.spec.node_name \
                    and uid not in assumed:
                report.pods_misplaced.append((st.pod, p))
        return report

    def _drift_report_incremental(self, hub, since_rv: int
                                  ) -> DriftReport:
        """O(changes) comparer: fetch the journal suffix after
        ``since_rv`` (``hub.list_changes``), reduce it to the LAST
        event per object (intermediate states are moot — only the
        final hub truth can disagree with the cache), and compare just
        those objects. The finding categories match the full diff
        exactly, so ``repair_from_hub`` consumes either report."""
        changes_fn = getattr(hub, "list_changes", None)
        if changes_fn is None:
            # a hub without the incremental surface: the caller's
            # RvTooOld ladder lands on the full diff
            raise RvTooOld("drift", since_rv, 0)
        try:
            res = changes_fn(since_rv, ("pods", "nodes"))
        except (ValueError, TypeError):
            # a pre-fabric REMOTE peer: "unknown method list_changes"
            # crosses the /call wire as its 400 ValueError. Same ladder
            # as a compacted gap — fall back to the full diff instead
            # of crashing the maintenance loop every interval.
            # (Unavailable keeps propagating: that is hub-down, not
            # version skew.)
            raise RvTooOld("drift", since_rv, 0) from None
        if res.get("too_old"):
            raise RvTooOld("drift", since_rv,
                           res.get("compacted_rv", 0))
        report = DriftReport()
        report.rv = res.get("rv")
        report.incremental = True
        # last event per object wins. Nodes reduce by NAME (the full
        # diff — and the cache — key nodes by name): a delete+recreate
        # under the same name must collapse to the final add, not
        # survive as a delete for the old uid that would repair a LIVE
        # node out of the cache. Pods reduce by uid, their cache key.
        final: dict[tuple, dict] = {}
        for ch in res.get("changes", ()):
            obj = ch.get("obj")
            if obj is None:
                continue
            key = obj.metadata.name if ch["kind"] == "nodes" \
                else obj.metadata.uid
            final[(ch["kind"], key)] = ch
        if not final:
            return report
        with self._lock:
            cached_nodes = set(self._nodes)
            cached_pods = {uid: st for uid, st
                           in self._pod_states.items()}
            assumed = set(self._assumed_pods)
        for (kind, uid), ch in sorted(final.items(),
                                      key=lambda kv: kv[1]["rv"]):
            obj = ch["obj"]
            if kind == "nodes":
                name = obj.metadata.name
                if ch["type"] == "delete":
                    if name in cached_nodes:
                        report.nodes_stale.append(name)
                elif name not in cached_nodes:
                    report.nodes_missing.append(obj)
                continue
            # pods: the full diff compares against BOUND hub pods only
            st = cached_pods.get(uid)
            if ch["type"] == "delete" or not obj.spec.node_name:
                if st is not None and uid not in assumed:
                    report.pods_stale.append(st.pod)
            elif st is None:
                report.pods_missing.append(obj)
            elif st.pod.spec.node_name != obj.spec.node_name \
                    and uid not in assumed:
                report.pods_misplaced.append((st.pod, obj))
        return report

    def compare_with_hub(self, hub) -> list[str]:
        """Human-readable drift lines (the SIGUSR2 debug surface; the
        drift sentinel consumes the structured drift_report instead)."""
        return self.drift_report(hub).render()

    def repair_from_hub(self, hub, report: Optional[DriftReport] = None
                        ) -> int:
        """Targeted drift repair: mutate ONLY the drifted entries back to
        hub truth (generation bumps make the incremental snapshot refresh
        re-pack exactly those rows — no full relist, no cache rebuild).
        Returns the number of repairs applied. Re-checks each finding
        against the live cache under the lock: a finding the informer
        already fixed (or that became an assumed-pod optimistic write)
        is skipped, not clobbered."""
        if report is None:
            report = self.drift_report(hub)
        repaired = 0
        with self._lock:
            for name in report.nodes_stale:
                item = self._nodes.get(name)
                if item is None:
                    continue
                if item.info.node is not None:
                    self._node_tree.remove_node(item.info.node)
                self._node_set_version += 1
                if item.info.pods:
                    item.info.remove_node()
                    self._move_to_head(item)
                else:
                    self._remove_from_list(item)
                    del self._nodes[name]
                repaired += 1
        for node in report.nodes_missing:
            with self._lock:
                item = self._nodes.get(node.metadata.name)
                if item is not None and item.info.node is not None:
                    continue            # informer beat us to it
            self.add_node(node)
            repaired += 1
        for pod in report.pods_stale:
            uid = pod.metadata.uid
            with self._lock:
                st = self._pod_states.get(uid)
                if st is None or st.assumed:
                    continue            # gone, or an optimistic write
            self.remove_pod(pod)
            repaired += 1
        for pod in report.pods_missing:
            uid = pod.metadata.uid
            with self._lock:
                if uid in self._pod_states:
                    continue
            self.add_pod(pod)
            repaired += 1
        for cached, p in report.pods_misplaced:
            uid = p.metadata.uid
            with self._lock:
                st = self._pod_states.get(uid)
                if st is None or st.assumed \
                        or st.pod.spec.node_name == p.spec.node_name:
                    continue
            self.update_pod(cached, p)
            repaired += 1
        return repaired

    def dump(self) -> dict:
        """Cache debugger surface (backend/cache/debugger): nodes + pods +
        assumed set, for the SIGUSR2-style comparer."""
        with self._lock:
            return {
                "nodes": {
                    name: {
                        "pods": [pi.pod.key() for pi in it.info.pods],
                        "requested_milli_cpu": it.info.requested.milli_cpu,
                        "generation": it.info.generation,
                    }
                    for name, it in self._nodes.items()
                },
                "assumed_pods": sorted(self._assumed_pods),
            }
