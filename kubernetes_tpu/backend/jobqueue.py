"""The multi-tenant job-queue layer in front of the activeQ.

What Kant (PAPERS.md) calls job-level queues, grafted onto the batched
scheduling core: pods carrying the tenant label (``LABEL_QUEUE``) or a
gang label (``LABEL_POD_GROUP``) are held here — NOT in the
PriorityQueue — until their tenant's turn and quota admit them. Release
order across tenants is **weighted deficit round robin** (each tenant
accrues ``weight x quantum`` credit per round and spends one credit per
pod released), so a 2:1 weight ratio yields a 2:1 admission ratio under
contention without starving anyone. Quota is **admission-time
reservation** (the Kueue discipline): a tenant's requests-based usage
(api.resources.pod_request) is charged when its pods are released into
the scheduling batch (or observed already bound at startup replay) and
credited back when they are deleted; a unit that would exceed quota
stays queued without blocking the tenant's smaller units or any other
tenant.

Gang-aware release: pods of one PodGroup form a single release **unit**
that becomes eligible only when the group object is known, at least
``min_member`` members are present, and the whole unit fits the
tenant's remaining quota — the queue half of all-or-nothing admission
(the commit half lives in plugins/gang.py and the device gang packer).
Pods whose group has not arrived yet park in an orphan pool and join
their tenant when it does.

Gang-aware backfill: an ELIGIBLE gang waiting only on DRR credit at the
head of its tenant's queue earmarks the deficit (it accrues for the
gang, never spent by others — the bounded-wait guarantee), while
SINGLE-pod jobs behind it flow around on **backfill debt** capped at
one blocked-gang's cost; the debt repays from the deficit the moment
the gang releases, so the contended admission ratio converges back to
the configured weights (sibling gangs never ride debt — the contended
gang ratio stays the weight ratio).

Pods with neither label never touch this layer: the scheduler routes
them straight to the PriorityQueue, and the per-cycle release step is
gated on ``active`` — one attribute read — so the non-gang hot path
pays nothing.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from itertools import islice
from typing import Callable, Optional

from kubernetes_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_QUEUE,
    Pod,
    PodGroup,
    pod_group_key,
)
from kubernetes_tpu.api.resources import Resource, pod_request

DEFAULT_TENANT = "default"

# DRR credit granted per tenant per round, scaled by weight; cost is one
# credit per pod, so weights read directly as admission ratios
DRR_QUANTUM = 1.0


class _Unit:
    """One release unit: a single pod, or a (possibly still assembling)
    gang of pods sharing a PodGroup."""

    __slots__ = ("gang_key", "pods", "seq")

    def __init__(self, gang_key: Optional[str], seq: int):
        self.gang_key = gang_key
        self.pods: "OrderedDict[str, Pod]" = OrderedDict()  # uid -> pod
        self.seq = seq

    def __len__(self) -> int:
        return len(self.pods)


class _Tenant:
    def __init__(self, name: str, weight: float = 1.0,
                 quota: Optional[Resource] = None,
                 quota_pods: int = 0):
        self.name = name
        self.weight = max(weight, 0.0) or 1.0
        self.quota = quota                  # None = unlimited
        self.quota_pods = quota_pods        # 0 = unlimited
        self.usage = Resource()
        self.usage_pods = 0
        self.deficit = 0.0
        # gang-aware backfill debt: pods released AROUND a credit-gated
        # gang at the head of this tenant's queue (charged here, not to
        # the deficit the gang is accruing), repaid from the deficit
        # after the gang releases so long-run ratios converge to weight
        self.backfill_debt = 0.0
        # a full scan found nothing releasable and nothing awaiting mere
        # credit (all units quota-blocked or assembling): the tenant's
        # turn is SKIPPED until an event that could unblock it (a pod
        # added, its group arriving, a quota credit, a bound member) —
        # re-probing a 300-unit blocked backlog every DRR rotation was
        # the QuotaExhaustionChurn hot spot (ISSUE 12)
        self.idle = False
        # release order within the tenant: FIFO over units
        self.units: "OrderedDict[str, _Unit]" = OrderedDict()  # key -> unit
        # admission bookkeeping
        self.admitted = 0                   # pods released, lifetime
        # pods released while ANOTHER tenant also had backlog: under
        # contention these track the configured weight ratios (the
        # fairness number the gang-storm bench publishes — lifetime
        # totals converge to 1:1 once the faster tenant drains)
        self.contended_admitted = 0
        self.quota_blocked = 0              # release attempts quota denied

    def depth(self) -> int:
        return sum(len(u) for u in self.units.values())

    def fits_quota(self, req: Resource, n_pods: int) -> bool:
        if self.quota_pods and self.usage_pods + n_pods > self.quota_pods:
            return False
        q = self.quota
        if q is None:
            return True
        u = self.usage
        if u.milli_cpu + req.milli_cpu > q.milli_cpu > 0:
            return False
        if u.memory + req.memory > q.memory > 0:
            return False
        if u.ephemeral_storage + req.ephemeral_storage \
                > q.ephemeral_storage > 0:
            return False
        for k, v in req.scalar.items():
            cap = q.scalar.get(k, 0)
            if cap and u.scalar.get(k, 0) + v > cap:
                return False
        return True


def _unit_request(unit: _Unit) -> Resource:
    total = Resource()
    for pod in unit.pods.values():
        total.add(pod_request(pod))
    return total


class JobQueue:
    """Tenant queues + DRR release + quota accounting + gang gating."""

    def __init__(self, tenants: Optional[dict] = None,
                 now: Callable[[], float] = time.time,
                 bound_fn: Optional[Callable[[str], int]] = None):
        self._now = now
        self._tenants: "OrderedDict[str, _Tenant]" = OrderedDict()
        self._groups: dict[str, PodGroup] = {}       # gang key -> group
        # gang key -> count of members the informer has seen BOUND:
        # min_member gating must survive failover — a new leader releases
        # the TAIL of a half-bound gang (min_member minus bound) instead
        # of holding it behind a quorum of queued members that can never
        # assemble. The registry itself lives in the gang coordinator
        # (plugins/gang.py) — one copy, queried here — so the two quorum
        # counts cannot drift. None (standalone queue) counts zero bound.
        self._bound_fn = bound_fn
        # gang units whose PodGroup has not arrived: gang key -> unit
        self._orphans: dict[str, _Unit] = {}
        # BOUND gang members seen before their PodGroup (informer replays
        # pods before groups on restart): gang key -> uid -> pod. Their
        # quota charge is deferred to set_group — charging by the pod's
        # own label would misattribute the usage to the wrong tenant,
        # and the charge-once guard would make that permanent
        self._pending_bound: dict[str, dict[str, Pod]] = {}
        # uid -> (tenant name | None, unit key) for queued pods;
        # tenant None = orphan pool
        self._where: dict[str, tuple[Optional[str], str]] = {}
        # uids whose quota reservation is live (admitted or seen bound)
        self._charged: dict[str, tuple[str, Resource]] = {}
        self._seq = 0
        self._rr: list[str] = []            # DRR rotation order
        self._rr_i = 0
        # the scheduler's per-cycle gate: True once any tenant/gang pod
        # or group has ever been seen (one attribute read on hot path)
        self.active = False
        # brownout parking (scheduler overload self-protection): parked
        # tenants sit out the DRR rotation entirely — no releases, no
        # credit accrual (parking must not bank deficit the tenant
        # bursts through the moment pressure clears)
        self.parked: set[str] = set()
        for name, cfg in (tenants or {}).items():
            self.configure_tenant(name, **cfg)

    # ------------- configuration / groups -------------

    def configure_tenant(self, name: str, weight: float = 1.0,
                         quota: Optional[dict] = None) -> None:
        q = None
        q_pods = 0
        if quota:
            q = Resource.from_map({k: str(v) for k, v in quota.items()})
            q_pods = q.allowed_pod_number
        t = self._tenants.get(name)
        if t is None:
            self._tenants[name] = _Tenant(name, weight, q, q_pods)
            self._rr.append(name)
        else:
            t.weight = max(weight, 0.0) or 1.0
            t.quota, t.quota_pods = q, q_pods
            t.idle = False          # quota change may unblock the scan
        self.active = True

    def set_group(self, group: PodGroup) -> None:
        """PodGroup arrived/changed: adopt any orphaned members into the
        group's tenant queue."""
        key = group.key()
        self._groups[key] = group
        self.active = True
        t = self._tenant_for_name(group.queue)
        t.idle = False              # its gang may now be releasable
        # re-home a unit queued under any OTHER tenant (the group's queue
        # changed, or members routed by pod label before the group
        # arrived): a gang split across tenants can never assemble
        # min_member in either half, so the group's queue wins and the
        # halves merge
        for other in self._tenants.values():
            if other is t:
                continue
            stray = other.units.pop(key, None)
            if stray is None:
                continue
            home = t.units.get(key)
            if home is None:
                t.units[key] = stray
            else:
                home.pods.update(stray.pods)
            for uid in stray.pods:
                self._where[uid] = (t.name, key)
        orphan = self._orphans.pop(key, None)
        if orphan is not None:
            home = t.units.get(key)
            if home is None:
                t.units[key] = orphan
            else:
                home.pods.update(orphan.pods)
                orphan = home
            for uid in orphan.pods:
                self._where[uid] = (t.name, key)
        # charge bound members whose quota reservation waited on the
        # group's (authoritative) tenant
        pending = self._pending_bound.pop(key, None)
        if pending is not None:
            for pod in pending.values():
                self.note_bound(pod)

    def remove_group(self, key: str) -> None:
        self._groups.pop(key, None)
        # a deleted PodGroup must not wedge its queued members behind an
        # _eligible that can never pass again: the unit returns to the
        # orphan pool (the mirror of set_group's adoption), where it
        # re-joins a tenant if the group is re-created
        for t in self._tenants.values():
            unit = t.units.pop(key, None)
            if unit is not None:
                self._orphans[key] = unit
                for uid in unit.pods:
                    self._where[uid] = (None, key)
                break

    def group(self, key: str) -> Optional[PodGroup]:
        return self._groups.get(key)

    # ------------- routing -------------

    @staticmethod
    def wants(pod: Pod) -> bool:
        """Does this pod route through the job-queue layer? One/two dict
        probes — the whole tax non-tenant pods pay."""
        labels = pod.metadata.labels
        return LABEL_QUEUE in labels or LABEL_POD_GROUP in labels

    def holds(self, uid: str) -> bool:
        return uid in self._where

    def _tenant_for_name(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = _Tenant(name)
            self._tenants[name] = t
            self._rr.append(name)
        return t

    def _tenant_of(self, pod: Pod, group: Optional[PodGroup]) -> str:
        # the PodGroup's queue is authoritative for gang members:
        # routing by per-pod labels would split a gang with
        # inconsistent/missing labels into same-keyed units under
        # several tenants, none of which could ever reach min_member
        if group is not None:
            return group.queue
        name = pod.metadata.labels.get(LABEL_QUEUE)
        if name:
            return name
        return DEFAULT_TENANT

    # ------------- add / update / remove -------------

    def add(self, pod: Pod) -> None:
        """Queue one tenant/gang pod (idempotent per uid)."""
        self.active = True
        uid = pod.metadata.uid
        if uid in self._where:
            self.update(pod)
            return
        gang = pod_group_key(pod)
        if gang is not None:
            group = self._groups.get(gang)
            if group is None:
                unit = self._orphans.get(gang)
                if unit is None:
                    self._seq += 1
                    unit = self._orphans[gang] = _Unit(gang, self._seq)
                unit.pods[uid] = pod
                self._where[uid] = (None, gang)
                return
            t = self._tenant_for_name(self._tenant_of(pod, group))
            unit = t.units.get(gang)
            if unit is None:
                self._seq += 1
                unit = t.units[gang] = _Unit(gang, self._seq)
            unit.pods[uid] = pod
            self._where[uid] = (t.name, gang)
            t.idle = False          # the gang may now be assembled
            return
        t = self._tenant_for_name(self._tenant_of(pod, None))
        self._seq += 1
        key = f"pod:{uid}"
        unit = t.units[key] = _Unit(None, self._seq)
        unit.pods[uid] = pod
        self._where[uid] = (t.name, key)
        t.idle = False              # fresh releasable work

    def update(self, pod: Pod) -> None:
        where = self._where.get(pod.metadata.uid)
        if where is None:
            self.add(pod)
            return
        tenant, key = where
        pool = (self._orphans if tenant is None
                else self._tenants[tenant].units)
        unit = pool.get(key)
        if unit is not None and pod.metadata.uid in unit.pods:
            unit.pods[pod.metadata.uid] = pod

    def remove(self, pod: Pod) -> None:
        """Pod deleted (or left our jurisdiction): drop from any queue
        and credit back its quota reservation."""
        uid = pod.metadata.uid
        where = self._where.pop(uid, None)
        if where is not None:
            tenant, key = where
            pool = (self._orphans if tenant is None
                    else self._tenants[tenant].units)
            unit = pool.get(key)
            if unit is not None:
                unit.pods.pop(uid, None)
                if not unit.pods:
                    pool.pop(key, None)
        gang = pod_group_key(pod)
        if gang is not None:
            pending = self._pending_bound.get(gang)
            if pending is not None:
                pending.pop(uid, None)
                if not pending:
                    del self._pending_bound[gang]
        charged = self._charged.pop(uid, None)
        if charged is not None:
            tname, req = charged
            t = self._tenants.get(tname)
            if t is not None:
                t.usage.sub(req)
                t.usage_pods -= 1
                t.idle = False      # quota credit may unblock the scan
        if where is not None and where[0] is not None:
            t = self._tenants.get(where[0])
            if t is not None:
                t.idle = False      # a shrunk unit may now fit quota

    def drain_unowned(self, owns: Callable[[Pod], bool]) -> list[Pod]:
        """Scale-out rebalance support: remove and return every queued
        pod whose UNIT ``owns`` disclaims. Judged per unit, not per
        member — a gang routes whole by its PodGroup's ring slot
        (``pod_group_key`` carries the group's namespace, the hash
        input), so a rebalance mid-assembly re-homes the entire unit to
        the new owner instead of splitting members across replicas, the
        same never-split discipline ``set_group`` enforces across
        tenants. ``remove`` per member keeps the quota credit and
        pending-bound bookkeeping on the normal path."""
        out: list[Pod] = []
        pools = [t.units for t in self._tenants.values()]
        pools.append(self._orphans)
        for pool in pools:
            for unit in list(pool.values()):
                pods = list(unit.pods.values())
                if not pods or owns(pods[0]):
                    continue
                for pod in pods:
                    self.remove(pod)
                    out.append(pod)
        return out

    def note_bound(self, pod: Pod) -> None:
        """An already-bound tenant pod surfaced through the informer
        (startup replay / foreign bind): reserve its quota so admission
        accounting survives a scheduler restart."""
        uid = pod.metadata.uid
        if uid in self._charged:
            return
        self.active = True
        gang = pod_group_key(pod)
        group = self._groups.get(gang) if gang else None
        if gang is not None and group is None:
            # group not seen yet: defer the charge to set_group (the
            # group's queue is the authoritative tenant — see
            # _pending_bound)
            self._pending_bound.setdefault(gang, {})[uid] = pod
            return
        t = self._tenant_for_name(self._tenant_of(pod, group))
        req = pod_request(pod)
        t.usage.add(req)
        t.usage_pods += 1
        self._charged[uid] = (t.name, req)
        t.idle = False              # bound member: gang quorum moved

    # ------------- release (the DRR pop order) -------------

    def _eligible(self, t: _Tenant, unit: _Unit,
                  blocked_counted: Optional[set] = None) -> bool:
        """Is this unit releasable now? Gangs need their group object,
        min_member present members, and whole-unit quota fit; single
        pods just need quota. ``blocked_counted`` dedups the
        quota_blocked counter to one denial per unit per release() call
        (the same blocked head unit is re-probed every DRR round)."""
        if unit.gang_key is not None:
            group = self._groups.get(unit.gang_key)
            if group is None:
                return False
            # members the informer already saw bound count toward the
            # quorum: after failover the tail of a half-bound gang must
            # release (the same registry the Permit plugin's quorum uses)
            bound = (self._bound_fn(unit.gang_key)
                     if self._bound_fn is not None else 0)
            if len(unit) < max(group.min_member - bound, 1):
                return False
        req = _unit_request(unit)
        if not t.fits_quota(req, len(unit)):
            if blocked_counted is None or unit.seq not in blocked_counted:
                t.quota_blocked += 1
                if blocked_counted is not None:
                    blocked_counted.add(unit.seq)
            return False
        return True

    def _release_unit(self, t: _Tenant, key: str, unit: _Unit,
                      pq) -> int:
        t.units.pop(key, None)
        for uid, pod in unit.pods.items():
            self._where.pop(uid, None)
            if uid not in self._charged:    # charge-once per pod lifetime
                req = pod_request(pod)
                t.usage.add(req)
                t.usage_pods += 1
                self._charged[uid] = (t.name, req)
            pq.add(pod)
        t.admitted += len(unit)
        return len(unit)

    def was_admitted(self, uid: str) -> bool:
        """True once a pod's quota reservation is live (released into the
        scheduling batch, or observed bound): re-entries (relist replay,
        quarantine release) bypass the admission gate instead of being
        re-held behind min_member they already cleared."""
        return uid in self._charged

    def release(self, pq, budget: int = 256) -> int:
        """Admit up to ``budget`` pods into the PriorityQueue in weighted
        deficit-round-robin order across tenants; returns pods released.
        A gang unit releases whole or not at all (its cost may overdraw
        the remaining budget by design — splitting it would violate
        all-or-nothing admission)."""
        if not self._rr:
            return 0
        released = 0
        blocked_counted: set = set()
        # O(budget) guard: walk at most this many HEAD units per tenant
        # per round — an ineligible unit beyond the cap shadows later
        # ones until the head drains, which keeps a 100k-pod backlog
        # from costing a full scan every scheduling cycle
        scan_cap = max(budget * 4, 512)
        # one full rotation with credit accrual, repeated while progress
        # is being made (a tenant with deep backlog keeps its deficit)
        stalled_rounds = 0
        n = len(self._rr)
        while released < budget and stalled_rounds < 2:
            progressed = False
            # credit fast-forward: rounds until the NEAREST credit-gated
            # eligible gang could release (DRR rounds are virtual time —
            # when a rotation releases nothing, spinning real scheduling
            # cycles to accrue one quantum per call is pure dribble; all
            # tenants advance the SAME rounds, so ratios are untouched)
            ff_rounds = None
            for _ in range(n):
                name = self._rr[self._rr_i % len(self._rr)]
                self._rr_i += 1
                t = self._tenants[name]
                if name in self.parked:
                    t.deficit = 0.0     # parked must not bank credit
                    continue
                if not t.units:
                    # no backlog: credit must not bank, and backfill
                    # debt has no counterparty left to repay
                    t.deficit = 0.0
                    t.backfill_debt = 0.0
                    continue
                if t.idle:
                    # fully blocked backlog, nothing changed since the
                    # last full scan: skip the turn (deficit stays
                    # zeroed — blocked must not bank credit)
                    continue
                contended = any(o.units for o in self._tenants.values()
                                if o is not t)
                t.deficit += t.weight * DRR_QUANTUM
                # walk units in FIFO order, skipping ineligible ones
                # (an assembling gang must not block singles behind it)
                any_eligible = False
                budget_cut = False
                # gang-aware backfill: the first credit-gated gang on
                # this turn EARMARKS the deficit (it keeps accruing for
                # the gang, untouched); strictly smaller units behind it
                # may still flow, charged to bounded backfill debt
                gated_cost = 0
                for key in list(islice(t.units, scan_cap)):
                    if released >= budget:
                        budget_cut = True
                        break
                    unit = t.units.get(key)
                    if unit is None \
                            or not self._eligible(t, unit,
                                                  blocked_counted):
                        continue
                    any_eligible = True
                    cost = len(unit)
                    if contended:
                        if gated_cost:
                            # backfill around the earmarked gang:
                            # SINGLE-pod jobs only (a sibling gang
                            # riding debt would bend the contended
                            # gang-admission ratio off the configured
                            # weights), on debt capped at one
                            # blocked-gang's cost — the gang's release
                            # round is untouched (its deficit accrues
                            # whole), and the debt is repaid from
                            # post-release deficit so the contended
                            # ratio converges back to weight
                            if unit.gang_key is not None \
                                    or cost >= gated_cost \
                                    or t.backfill_debt + cost > gated_cost:
                                continue
                            t.backfill_debt += cost
                        else:
                            # credit gates releases only under
                            # contention — fairness has no counterparty
                            # when this tenant alone has backlog
                            if t.deficit < 1.0:
                                # eligible work awaits credit (e.g. the
                                # deficit is deep negative after a big
                                # gang's overdraw): record how far the
                                # virtual clock must advance for THIS
                                # head unit so an unproductive rotation
                                # can fast-forward instead of dribbling
                                need_credit = (min(cost, t.weight * 4)
                                               if cost > 1 else 1.0) \
                                    - t.deficit
                                rounds = need_credit / t.weight
                                if ff_rounds is None \
                                        or rounds < ff_rounds:
                                    ff_rounds = rounds
                                break
                            if cost > t.deficit and cost > 1 \
                                    and t.deficit < min(cost,
                                                        t.weight * 4):
                                # gang bigger than remaining credit:
                                # stop SPENDING (deficit accrues to the
                                # gang — singles must not spend it back
                                # to zero every round and starve it) but
                                # keep scanning for backfill
                                gated_cost = cost
                                need_credit = (min(cost, t.weight * 4)
                                               - t.deficit)
                                rounds = need_credit / t.weight
                                if ff_rounds is None or rounds < ff_rounds:
                                    ff_rounds = rounds
                                continue
                            t.deficit -= cost
                            if unit.gang_key is not None \
                                    and t.backfill_debt > 0.0:
                                # a gang released: repay backfill debt
                                # from what its earmark left behind —
                                # only from POSITIVE deficit (a big
                                # gang's overdraw leaves it negative;
                                # "repaying" from that would forgive
                                # the overdraw and inflate the debt)
                                pay = min(max(t.deficit, 0.0),
                                          t.backfill_debt)
                                t.deficit -= pay
                                t.backfill_debt -= pay
                    else:
                        t.deficit = 0.0
                        t.backfill_debt = 0.0
                    n_rel = self._release_unit(t, key, unit, pq)
                    released += n_rel
                    if contended:
                        t.contended_admitted += n_rel
                    progressed = True
                if not any_eligible and not budget_cut:
                    # quota-blocked / assembling backlog must not BANK
                    # credit (classic DRR zeroes an unproductive turn):
                    # banked deficit would let the tenant burst past its
                    # weight ratio the moment its units free up. Credit
                    # persists only while an ELIGIBLE unit awaits it.
                    t.deficit = 0.0
                    if gated_cost == 0 and len(t.units) <= scan_cap:
                        # the WHOLE backlog was scanned and every unit
                        # is quota-blocked or assembling: park the
                        # tenant until an unblocking event wakes it
                        t.idle = True
                if released >= budget:
                    break
            if not progressed and ff_rounds is not None and ff_rounds > 0:
                # nothing released but a credit-gated gang is waiting:
                # fast-forward the virtual clock just far enough that it
                # releases next rotation — every backlogged tenant
                # accrues the same rounds, preserving the weight ratios
                # exactly while cutting the one-quantum-per-call dribble
                adv = float(int(ff_rounds) + (ff_rounds % 1.0 > 0.0))
                for name in self._rr:
                    t = self._tenants[name]
                    # idle (fully blocked) tenants sit the rounds out:
                    # crediting them would BANK deficit the moment
                    # their quota frees — the invariant the zeroed
                    # unproductive turn enforces
                    if t.units and not t.idle \
                            and name not in self.parked:
                        t.deficit += t.weight * DRR_QUANTUM * adv
                progressed = True
            stalled_rounds = 0 if progressed else stalled_rounds + 1
        return released

    # ------------- brownout parking -------------

    def park_below(self, max_weight: float) -> list[str]:
        """Park every tenant whose weight is strictly below
        ``max_weight`` — the best-effort tier by the convention that
        weight encodes priority class. Parked tenants keep their
        backlog and quota charges; they simply stop releasing. Returns
        the names newly parked (sorted, for logs)."""
        newly = []
        for name, t in self._tenants.items():
            if t.weight < max_weight and name not in self.parked:
                self.parked.add(name)
                newly.append(name)
        return sorted(newly)

    def unpark_all(self) -> list[str]:
        """Brownout exit: every parked tenant rejoins the rotation.
        Idle flags clear so the next release() re-probes their
        backlogs. Returns the names freed (sorted)."""
        freed = sorted(self.parked)
        self.parked.clear()
        for name in freed:
            t = self._tenants.get(name)
            if t is not None:
                t.idle = False
        return freed

    # ------------- introspection -------------

    def pending_count(self) -> int:
        return (sum(t.depth() for t in self._tenants.values())
                + sum(len(u) for u in self._orphans.values()))

    def __len__(self) -> int:
        return self.pending_count()

    def tenant_stats(self) -> dict[str, dict]:
        """Per-tenant depth/usage/admission counters (metrics + debug)."""
        out = {}
        for name, t in self._tenants.items():
            out[name] = {
                "weight": t.weight,
                "parked": name in self.parked,
                "depth": t.depth(),
                "admitted": t.admitted,
                "contended_admitted": t.contended_admitted,
                "quota_blocked": t.quota_blocked,
                "backfill_debt": round(t.backfill_debt, 3),
                "usage": {"cpu_milli": t.usage.milli_cpu,
                          "memory": t.usage.memory,
                          "pods": t.usage_pods,
                          **{k: v for k, v in t.usage.scalar.items()}},
                "quota": (None if t.quota is None else {
                    "cpu_milli": t.quota.milli_cpu,
                    "memory": t.quota.memory,
                    "pods": t.quota_pods}),
            }
        return out

    def debug_state(self) -> dict:
        """The /debug/queue view: tenants + assembling gangs."""
        gangs = {}
        for name, t in self._tenants.items():
            for key, unit in t.units.items():
                if unit.gang_key is not None:
                    g = self._groups.get(unit.gang_key)
                    gangs[key] = {
                        "tenant": name,
                        "members_present": len(unit),
                        "min_member": g.min_member if g else None,
                    }
        for key, unit in self._orphans.items():
            gangs[key] = {"tenant": None, "members_present": len(unit),
                          "min_member": None, "orphan": True}
        return {"tenants": self.tenant_stats(), "gangs": gangs,
                "pending": self.pending_count()}
