"""Generic heap with a map index, as used by both activeQ and backoffQ.

Equivalent of /root/reference/pkg/scheduler/backend/heap/heap.go: a
binary heap keyed by an arbitrary less(a, b) with O(1) membership lookup,
update-in-place, and delete-by-key.

Two engines share the public API:

* When the ordering is expressible as a per-item numeric sort key (the
  default PrioritySort is: (-priority, enqueue time); backoff expiry is),
  pass ``sort_key_fn`` — the heap then runs on the C++ ``KeyedHeap``
  (kubernetes_tpu.native, src/_native.cpp) with all sift comparisons in
  native code. An item whose sort key is not coercible to (float, float)
  degrades the instance to the Python engine transparently.
* Otherwise (custom queue-sort plugins with arbitrary less semantics),
  a pure-Python binary heap calling less_fn.
"""

from __future__ import annotations

from typing import Callable, Generic, Optional, TypeVar

from kubernetes_tpu.native import mod as _native

T = TypeVar("T")


class Heap(Generic[T]):
    def __init__(self, key_fn: Callable[[T], str],
                 less_fn: Callable[[T, T], bool],
                 sort_key_fn: Optional[Callable[[T], tuple]] = None):
        self._key = key_fn
        self._less = less_fn
        self._sort_key = sort_key_fn
        # (map key, sort key or None, item); the map key rides along so
        # sifts never re-invoke key_fn
        self._entries: list[tuple[str, object, T]] = []
        self._index: dict[str, int] = {}
        self._nh = (_native.KeyedHeap()
                    if sort_key_fn is not None and _native is not None
                    else None)

    def __len__(self) -> int:
        if self._nh is not None:
            return len(self._nh)
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        if self._nh is not None:
            return key in self._nh
        return key in self._index

    def get(self, key: str) -> Optional[T]:
        if self._nh is not None:
            return self._nh.get(key)
        i = self._index.get(key)
        return self._entries[i][2] if i is not None else None

    @staticmethod
    def _as_double(x) -> float:
        """Sort-key component -> C double, ONLY when the conversion is
        order-preserving: real numbers within double precision. Numeric
        strings ('10' < '9' lexicographically, 10.0 > 9.0 numerically)
        and huge ints (>2^53 collapse to false ties) must degrade
        instead of silently reordering."""
        if isinstance(x, bool) or not isinstance(x, (int, float)):
            raise TypeError(f"non-numeric sort key {x!r}")
        if isinstance(x, int) and abs(x) > (1 << 53):
            raise TypeError("sort key beyond double precision")
        return float(x)

    def _degrade(self) -> None:
        """Move every native entry to the Python engine (an item produced
        a sort key the C heap can't order). The sort key is dropped
        entirely — a fn emitting non-numeric keys can't be trusted to emit
        mutually comparable ones either — so ordering reverts to less_fn,
        the authoritative comparator."""
        items, self._nh = self._nh.list(), None
        self._sort_key = None
        for it in items:
            self.add(it)

    def add(self, item: T) -> None:
        """Insert or update (re-heapify around the item); the sort key is
        (re)computed here, so updates that change ordering fields must go
        through add, as they always had to for less_fn correctness."""
        key = self._key(item)
        if self._nh is not None:
            sk = self._sort_key(item)
            try:
                if len(sk) > 2:
                    # >2 components can't ride the (a, b) engine without
                    # silently changing tie-breaks — degrade, don't truncate
                    raise TypeError
                a = self._as_double(sk[0])
                b = self._as_double(sk[1]) if len(sk) > 1 else 0.0
            except (TypeError, ValueError, IndexError):
                self._degrade()
            else:
                self._nh.add(key, a, b, item)
                return
        entry = (key, self._sort_key(item) if self._sort_key else None, item)
        i = self._index.get(key)
        if i is not None:
            self._entries[i] = entry
            self._down(self._up(i))
        else:
            self._entries.append(entry)
            self._index[key] = len(self._entries) - 1
            self._up(len(self._entries) - 1)

    def delete(self, key: str) -> Optional[T]:
        if self._nh is not None:
            return self._nh.delete(key)
        i = self._index.get(key)
        if i is None:
            return None
        return self._remove_at(i)

    def peek(self) -> Optional[T]:
        if self._nh is not None:
            return self._nh.peek()
        return self._entries[0][2] if self._entries else None

    def pop(self) -> Optional[T]:
        if self._nh is not None:
            return self._nh.pop()
        if not self._entries:
            return None
        return self._remove_at(0)

    def list(self) -> list[T]:
        if self._nh is not None:
            return self._nh.list()
        return [e[2] for e in self._entries]

    # ---- pure-Python engine internals ----

    def _lt(self, a: tuple[str, object, T], b: tuple[str, object, T]) -> bool:
        if self._sort_key is not None:
            return a[1] < b[1]
        return self._less(a[2], b[2])

    def _remove_at(self, i: int) -> T:
        entry = self._entries[i]
        last = len(self._entries) - 1
        self._swap(i, last)
        self._entries.pop()
        del self._index[entry[0]]
        if i < len(self._entries):
            self._down(self._up(i))
        return entry[2]

    def _swap(self, i: int, j: int) -> None:
        if i == j:
            return
        it, jt = self._entries[i], self._entries[j]
        self._entries[i], self._entries[j] = jt, it
        self._index[it[0]] = j
        self._index[jt[0]] = i

    def _up(self, i: int) -> int:
        entries = self._entries
        while i > 0:
            parent = (i - 1) // 2
            if self._lt(entries[i], entries[parent]):
                self._swap(i, parent)
                i = parent
            else:
                break
        return i

    def _down(self, i: int) -> None:
        entries = self._entries
        n = len(entries)
        while True:
            left, right = 2 * i + 1, 2 * i + 2
            smallest = i
            if left < n and self._lt(entries[left], entries[smallest]):
                smallest = left
            if right < n and self._lt(entries[right], entries[smallest]):
                smallest = right
            if smallest == i:
                return
            self._swap(i, smallest)
            i = smallest
