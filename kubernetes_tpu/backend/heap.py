"""Generic heap with a map index, as used by both activeQ and backoffQ.

Equivalent of /root/reference/pkg/scheduler/backend/heap/heap.go: a
binary heap keyed by an arbitrary less(a, b) with O(1) membership lookup,
update-in-place, and delete-by-key.
"""

from __future__ import annotations

from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class Heap(Generic[T]):
    def __init__(self, key_fn: Callable[[T], str],
                 less_fn: Callable[[T, T], bool]):
        self._key = key_fn
        self._less = less_fn
        self._items: list[T] = []
        self._index: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get(self, key: str) -> Optional[T]:
        i = self._index.get(key)
        return self._items[i] if i is not None else None

    def add(self, item: T) -> None:
        """Insert or update (re-heapify around the item)."""
        key = self._key(item)
        i = self._index.get(key)
        if i is not None:
            self._items[i] = item
            self._down(self._up(i))
        else:
            self._items.append(item)
            self._index[key] = len(self._items) - 1
            self._up(len(self._items) - 1)

    def delete(self, key: str) -> Optional[T]:
        i = self._index.get(key)
        if i is None:
            return None
        return self._remove_at(i)

    def peek(self) -> Optional[T]:
        return self._items[0] if self._items else None

    def pop(self) -> Optional[T]:
        if not self._items:
            return None
        return self._remove_at(0)

    def list(self) -> list[T]:
        return list(self._items)

    # ---- internals ----

    def _remove_at(self, i: int) -> T:
        item = self._items[i]
        last = len(self._items) - 1
        self._swap(i, last)
        self._items.pop()
        del self._index[self._key(item)]
        if i < len(self._items):
            self._down(self._up(i))
        return item

    def _swap(self, i: int, j: int) -> None:
        if i == j:
            return
        it, jt = self._items[i], self._items[j]
        self._items[i], self._items[j] = jt, it
        self._index[self._key(it)] = j
        self._index[self._key(jt)] = i

    def _up(self, i: int) -> int:
        while i > 0:
            parent = (i - 1) // 2
            if self._less(self._items[i], self._items[parent]):
                self._swap(i, parent)
                i = parent
            else:
                break
        return i

    def _down(self, i: int) -> None:
        n = len(self._items)
        while True:
            left, right = 2 * i + 1, 2 * i + 2
            smallest = i
            if left < n and self._less(self._items[left],
                                       self._items[smallest]):
                smallest = left
            if right < n and self._less(self._items[right],
                                        self._items[smallest]):
                smallest = right
            if smallest == i:
                return
            self._swap(i, smallest)
            i = smallest
