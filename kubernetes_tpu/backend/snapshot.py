"""Per-cycle immutable cluster view.

Equivalent of /root/reference/pkg/scheduler/backend/cache/snapshot.go:29-44:
a node map plus a zone-interleaved node list and the two affinity sublists
(HavePodsWithAffinityNodeInfoList / HavePodsWithRequiredAntiAffinityNodeInfoList)
that let InterPodAffinity's PreFilter scan only relevant nodes.

The snapshot is refreshed *incrementally* by Cache.update_snapshot (the
generation-diff walk of cache.go:186 UpdateSnapshot); the device mirror in
``backend.mirror`` applies the same diff to HBM rows.
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.backend.node_info import NodeInfo


class Snapshot:
    def __init__(self) -> None:
        self.node_info_map: dict[str, NodeInfo] = {}
        self.node_info_list: list[NodeInfo] = []
        self.have_pods_with_affinity_list: list[NodeInfo] = []
        self.have_pods_with_required_anti_affinity_list: list[NodeInfo] = []
        self.generation: int = 0
        # namespace name -> labels, for affinity namespaceSelector unrolling
        # (the nsLister surface of interpodaffinity/plugin.go:123)
        self.namespaces: dict[str, dict[str, str]] = {}
        self.ns_generation: int = 0
        # monotonically bumped by Cache.update_snapshot whenever anything in
        # the snapshot changed — lets downstream consumers (Mirror.sync) be
        # O(1) no-ops between changes
        self.version: int = 0
        self.node_set_version: int = -1

    # --- lister surface (snapshot.go:158-199) ---

    def num_nodes(self) -> int:
        return len(self.node_info_list)

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)

    def list_all(self) -> list[NodeInfo]:
        return self.node_info_list

    def index_of(self, name: str) -> int:
        """Stable row index of a node in this snapshot (device tensor row)."""
        for i, ni in enumerate(self.node_info_list):
            if ni.name == name:
                return i
        return -1
