"""Nominated-node bookkeeping for preemptor pods.

Equivalent of /root/reference/pkg/scheduler/backend/queue/nominator.go:35:
pods that triggered preemption carry status.nominatedNodeName while their
victims terminate; the scheduler reserves their room during other pods'
filtering (the mirror packs them as nominated table pods, see
Mirror.set_nominated) so the vacated space is not stolen.
"""

from __future__ import annotations

import threading

from kubernetes_tpu.api.objects import Pod


class Nominator:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._node_of: dict[str, str] = {}          # pod uid -> node name
        self._pods: dict[str, Pod] = {}             # pod uid -> pod object

    def add(self, pod: Pod, node_name: str) -> None:
        """AddNominatedPod (nominator.go:68); replaces a prior nomination."""
        with self._lock:
            self._node_of[pod.metadata.uid] = node_name
            self._pods[pod.metadata.uid] = pod

    def delete(self, uid: str) -> None:
        with self._lock:
            self._node_of.pop(uid, None)
            self._pods.pop(uid, None)

    def update(self, pod: Pod) -> None:
        """Refresh the stored pod object (labels/spec may have changed); the
        nomination itself follows status.nominatedNodeName."""
        with self._lock:
            uid = pod.metadata.uid
            if uid in self._node_of:
                if pod.status.nominated_node_name:
                    self._node_of[uid] = pod.status.nominated_node_name
                    self._pods[uid] = pod
                else:
                    self._node_of.pop(uid, None)
                    self._pods.pop(uid, None)
            elif pod.status.nominated_node_name:
                self._node_of[uid] = pod.status.nominated_node_name
                self._pods[uid] = pod

    def node_of(self, uid: str) -> str | None:
        with self._lock:
            return self._node_of.get(uid)

    def by_node(self) -> dict[str, list[Pod]]:
        """node name -> nominated pods (the mirror overlay feed)."""
        with self._lock:
            out: dict[str, list[Pod]] = {}
            for uid, node in self._node_of.items():
                out.setdefault(node, []).append(self._pods[uid])
            return out

    def clear_for_node_below_priority(self, node_name: str,
                                      priority: int) -> list[Pod]:
        """Drop nominations of LOWER-priority pods on a node (preemption.go
        prepareCandidate clears them so they re-evaluate); returns them."""
        with self._lock:
            dropped = [self._pods[uid] for uid, n in self._node_of.items()
                       if n == node_name
                       and self._pods[uid].priority() < priority]
            for p in dropped:
                self._node_of.pop(p.metadata.uid, None)
                self._pods.pop(p.metadata.uid, None)
            return dropped
